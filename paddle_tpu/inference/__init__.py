"""paddle.inference parity — the deployment-facing Predictor facade.

Reference: paddle/fluid/inference/api/ (AnalysisPredictor
analysis_predictor.cc, paddle_inference_api.h Config/Predictor/Tensor)
+ python surface paddle.inference.{Config, create_predictor}.

TPU mapping: the saved artifact is jit.save's StableHLO + weights (the
AnalysisPredictor's optimized program role — XLA *is* the analysis/
optimization pass stack here), and the Predictor is a thin handle-based
facade over TranslatedLayer so reference deployment code ports by
renaming imports.  GPU/MKLDNN/TensorRT config knobs are accepted and
recorded (XLA owns those decisions on TPU).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor"]


class Config:
    """paddle_infer.Config parity (the knobs that matter here: model
    path; device selection collapses to wherever jax put the program)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None,
                 decrypt_key=None):
        # jit.save writes <path>.pdmodel/<path>.pdparams — accept either
        # the bare prefix or the .pdmodel path
        p = prog_file or ""
        if p.endswith(".pdmodel"):
            p = p[: -len(".pdmodel")]
        self.model_prefix = p
        self._use_gpu = False
        self._enable_profile = False
        self._flags: Dict[str, object] = {}
        self._decrypt_key = decrypt_key

    def set_cipher_key(self, key):
        """Key for models saved with jit.save(..., encrypt_key=...) —
        the encrypted-deployment path (reference:
        analysis_predictor.cc:145 loading through AESCipher)."""
        self._decrypt_key = key

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        p = prog_file or ""
        if p.endswith(".pdmodel"):
            p = p[: -len(".pdmodel")]
        self.model_prefix = p          # paths only; knobs stay configured

    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0):
        self._use_gpu = True          # accepted; device is XLA's choice

    def disable_gpu(self):
        self._use_gpu = False

    def enable_profile(self):
        self._enable_profile = True

    def switch_ir_optim(self, on: bool = True):
        self._flags["ir_optim"] = on  # XLA always optimizes

    def enable_mkldnn(self):
        self._flags["mkldnn"] = True  # n/a on TPU, recorded for parity

    def enable_tensorrt_engine(self, **kw):
        self._flags["tensorrt"] = kw  # n/a on TPU, recorded for parity

    def model_dir(self):
        return self.model_prefix


class PredictorTensor:
    """paddle_infer.Tensor parity: named handle with copy_from/to_cpu."""

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[np.ndarray] = None

    def copy_from_cpu(self, arr):
        self._value = np.asarray(arr)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"tensor {self.name} has no value yet — "
                               "call Predictor.run() first")
        return self._value


class Predictor:
    """paddle_infer.Predictor parity over a TranslatedLayer."""

    def __init__(self, config: Config):
        from paddle_tpu import jit
        self.config = config
        self._layer = jit.load(config.model_prefix,
                               decrypt_key=config._decrypt_key)
        n_in = max(1, len(getattr(self._layer._exported, "in_avals", []))
                   - len(self._layer._params))
        self._inputs = {f"input_{i}": PredictorTensor(f"input_{i}")
                        for i in range(n_in)}
        self._outputs: Dict[str, PredictorTensor] = {}

    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name: str) -> PredictorTensor:
        return self._inputs[name]

    def run(self):
        import paddle_tpu as paddle
        args = []
        for name, h in self._inputs.items():
            if h._value is None:
                raise RuntimeError(f"input {name} not set")
            args.append(paddle.to_tensor(h._value))
        if self.config._enable_profile:
            from paddle_tpu.profiler import RecordEvent
            with RecordEvent("Predictor.run"):
                out = self._layer(*args)
        else:
            out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = {}
        for i, o in enumerate(outs):
            t = PredictorTensor(f"output_{i}")
            t._value = np.asarray(o.numpy())
            self._outputs[t.name] = t
        return True

    def get_output_names(self) -> List[str]:
        return list(self._outputs)

    def get_output_handle(self, name: str) -> PredictorTensor:
        return self._outputs[name]


def create_predictor(config: Config) -> Predictor:
    """paddle.inference.create_predictor parity."""
    return Predictor(config)
