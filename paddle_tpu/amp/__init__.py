"""Automatic mixed precision.

Parity targets: python/paddle/amp/auto_cast.py (:20) + grad_scaler.py (:20);
reference engine: imperative/amp_auto_cast.{h,cc} (AmpOperators white/black
lists :31, AutoCastGuard :58) and the AMP ops
operators/amp/check_finite_and_unscale_op, update_loss_scaling_op.

TPU-first: the compute dtype is bfloat16 (MXU native), which has fp32's
exponent range — so loss scaling is a no-op by default (GradScaler keeps the
reference's API and its dynamic-scaling state machine for fp16 mode, but
``enable=True`` with bf16 performs identity scaling).  auto_cast hooks the
tape's apply() to cast op inputs per white/black list, exactly the role of
AmpOperators in the reference tracer.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
import jax.numpy as jnp

import paddle_tpu.core as core
from paddle_tpu.core import Tensor

__all__ = ["auto_cast", "decorate", "GradScaler", "white_list", "black_list"]

# op-name lists mirroring imperative/amp_auto_cast.cc AmpOperators
white_list = {
    "matmul", "mm", "bmm", "mv", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "linear",
    "einsum", "flash_attention", "sdp_attention", "addmm",
}
black_list = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "mean", "sum",
    "softmax", "log_softmax", "cross_entropy", "nll_loss", "bce_with_logits",
    "binary_cross_entropy", "mse_loss", "l1_loss", "smooth_l1_loss", "kl_div",
    "layer_norm", "batch_norm", "instance_norm", "group_norm", "norm",
    "cumsum", "softmax_with_cross_entropy", "pow", "square", "sqrt", "rsqrt",
}

_amp_state = threading.local()


def _amp_level() -> Optional[str]:
    return getattr(_amp_state, "level", None)


def _amp_dtype():
    return getattr(_amp_state, "dtype", jnp.bfloat16)


def _amp_custom_white():
    return getattr(_amp_state, "custom_white", set())


def _amp_custom_black():
    return getattr(_amp_state, "custom_black", set())


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast parity; `float16` maps to bfloat16 on TPU unless
    explicitly forced (bf16 is the hardware-native mixed dtype)."""
    prev = (_amp_level(), _amp_dtype(), _amp_custom_white(),
            _amp_custom_black())
    prev_hook = core._amp_hook[0]
    if enable:
        _amp_state.level = level
        _amp_state.dtype = jnp.bfloat16 if str(dtype) in (
            "bfloat16", "bf16", "float16", "fp16") else jnp.dtype(dtype)
        _amp_state.custom_white = set(custom_white_list or ())
        _amp_state.custom_black = set(custom_black_list or ())
        core._amp_hook[0] = amp_cast_for_op
    else:
        _amp_state.level = None
    try:
        yield
    finally:
        (_amp_state.level, _amp_state.dtype, _amp_state.custom_white,
         _amp_state.custom_black) = prev
        core._amp_hook[0] = prev_hook


amp_guard = auto_cast


def amp_cast_for_op(name: str, args):
    """Called by core.apply when an amp level is active: cast float tensor
    args to the amp dtype for white-listed ops, to fp32 for black-listed ops
    (O1); O2 casts everything except black list."""
    level = _amp_level()
    if level is None:
        return args
    dtype = _amp_dtype()
    cw, cb = _amp_custom_white(), _amp_custom_black()
    in_white = (name in white_list or name in cw) and name not in cb
    in_black = name in black_list or name in cb

    # Casting must stay differentiable → do it through the tape
    from paddle_tpu.core import apply1
    def cast_tensor(a, to):
        if not isinstance(a, Tensor):
            return a
        if not jnp.issubdtype(a.dtype, jnp.floating) or a.dtype == jnp.dtype(to):
            return a
        return apply1(lambda x: x.astype(to), a, name="amp_cast")

    if level == "O2":
        if in_black:
            return [cast_tensor(a, jnp.float32) for a in args]
        return [cast_tensor(a, dtype) for a in args]
    if in_white:
        return [cast_tensor(a, dtype) for a in args]
    if in_black:
        return [cast_tensor(a, jnp.float32) for a in args]
    return args


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity: O2 casts parameters to the amp dtype
    (master fp32 copies kept by the optimizer when master_weight)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        dt = jnp.bfloat16 if str(dtype) in ("bfloat16", "bf16", "float16",
                                            "fp16") else jnp.dtype(dtype)
        for m in model_list:
            for p in m.parameters():
                if jnp.issubdtype(p.dtype, jnp.floating):
                    p.master_data = p._data  # fp32 master copy
                    p._data = p._data.astype(dt)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py +
    update_loss_scaling_op).  With bf16 (TPU default) scaling is identity;
    the fp16 state machine is kept for parity and CPU tests."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # numerics telemetry: consecutive scale DECREASES with no good
        # step in between — K of them is a loss-scale collapse
        # (numerics.scale_collapse flight event), the systematic-
        # overflow signal the GradScaler/ResilientTrainStep coop
        # previously had no observability for
        self._consecutive_downscales = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        from paddle_tpu.tensor.math import scale as _scale
        return _scale(var, self._scale)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        # one device computation + ONE host sync for the whole parameter
        # list (check_finite_and_unscale is a single fused op in the
        # reference too — operators/amp/check_finite_and_unscale_op)
        from paddle_tpu.framework.selected_rows import SelectedRows
        grads = [p._grad for p in optimizer._parameter_list or []
                 if p._grad is not None]
        if not grads:
            self._found_inf = False
            return
        # SelectedRows grads unscale their row values in place (the
        # reference's check_finite_and_unscale handles SelectedRows too)
        scaled = [(g.values if isinstance(g, SelectedRows) else g._data)
                  * inv for g in grads]
        flags = jnp.stack([jnp.any(~jnp.isfinite(s)) for s in scaled])
        for g, s in zip(grads, scaled):
            if isinstance(g, SelectedRows):
                g.values = s
            else:
                g._data = s
        self._found_inf = bool(jnp.any(flags))

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not self._enable or not self._dynamic:
            return
        # local import: amp loads with the core tensor tier, before the
        # framework observability planes need to exist
        from paddle_tpu.framework import monitor
        from paddle_tpu.framework.flags import flag
        from paddle_tpu.framework.observability import flight
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
                self._consecutive_downscales += 1
                k = int(flag("numerics_scale_collapse_k"))
                if k > 0 and self._consecutive_downscales >= k and \
                        self._consecutive_downscales % k == 0:
                    # K downscales with no good step between them: the
                    # overflow is systematic, not a transient batch
                    cd = self._consecutive_downscales
                    flight.record("numerics.scale_collapse",
                                  severity="warn", scale=self._scale,
                                  consecutive_downscales=cd)
                    monitor.stat_add("amp_scale_collapses_total")
        else:
            self._good_steps += 1
            self._bad_steps = 0
            self._consecutive_downscales = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        monitor.stat_set("amp_loss_scale", self._scale)
        self._found_inf = False

    def tighten_growth(self, factor: float = 4.0) -> dict:
        """Slow scale growth after a collapse: multiply the growth
        interval (``incr_every_n_steps``) by ``factor`` and cap the
        current scale at its present value as the new ceiling is
        re-approached more cautiously.  Returns the previous growth
        state (``incr_every_n_steps`` + ``good_steps``) so the caller
        — the autopilot's rollback guard — can undo the action via
        :meth:`restore_growth` if it did not help."""
        prev = {"incr_every_n_steps": self._incr_every,
                "good_steps": self._good_steps}
        self._incr_every = max(1, int(self._incr_every * factor))
        self._good_steps = 0
        return prev

    def restore_growth(self, prev: dict) -> None:
        """Undo a :meth:`tighten_growth` with the dict it returned."""
        self._incr_every = max(1, int(
            prev.get("incr_every_n_steps", self._incr_every)))
        self._good_steps = int(prev.get("good_steps", self._good_steps))

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps,
                "consecutive_downscales": self._consecutive_downscales}

    def set_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)
        # restore (or, for a pre-telemetry checkpoint, reset) the
        # collapse streak — a stale streak from this object's past life
        # must not fire a spurious numerics.scale_collapse
        self._consecutive_downscales = sd.get("consecutive_downscales", 0)
