"""Monitor counters — parity with the reference's StatRegistry
(paddle/fluid/platform/monitor.h:77, STAT_ADD/STAT_SUB macros at
monitor.h:135-141 and the python surface in fluid/core stats).

Process-wide named int/float counters that subsystems bump cheaply and
operators/loggers read for observability (the reference uses them for
e.g. STAT_gpu_mem, sparse table hit rates).  Thread-safe.
"""
from __future__ import annotations

import threading
from typing import Dict, Union

Number = Union[int, float]


class _Stat:
    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, value: Number = 0):
        self.name = name
        self.value = value
        self._lock = threading.Lock()

    def increase(self, v: Number = 1):
        with self._lock:
            self.value += v

    def decrease(self, v: Number = 1):
        with self._lock:
            self.value -= v

    def reset(self):
        with self._lock:
            self.value = 0


class StatRegistry:
    """monitor.h:77 StatRegistry<T>, without the int/float template split —
    python numbers unify both instantiations."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def get(self, name: str) -> _Stat:
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = _Stat(name)
            return s

    def stats(self) -> Dict[str, Number]:
        with self._lock:
            return {n: s.value for n, s in self._stats.items()}

    def reset_all(self):
        with self._lock:
            for s in self._stats.values():
                s.reset()


def stat_add(name: str, value: Number = 1):
    """STAT_ADD / STAT_INT_ADD / STAT_FLOAT_ADD (monitor.h:135,140)."""
    StatRegistry.instance().get(name).increase(value)


def stat_sub(name: str, value: Number = 1):
    StatRegistry.instance().get(name).decrease(value)


def get_stat(name: str) -> Number:
    return StatRegistry.instance().get(name).value


def reset_stat(name: str):
    StatRegistry.instance().get(name).reset()


def all_stats() -> Dict[str, Number]:
    return StatRegistry.instance().stats()


def reset_all_stats():
    StatRegistry.instance().reset_all()
