"""Monitor counters — parity with the reference's StatRegistry
(paddle/fluid/platform/monitor.h:77, STAT_ADD/STAT_SUB macros at
monitor.h:135-141 and the python surface in fluid/core stats).

Process-wide named int/float counters that subsystems bump cheaply and
operators/loggers read for observability (the reference uses them for
e.g. STAT_gpu_mem, sparse table hit rates).  Thread-safe.
"""
from __future__ import annotations

import threading
from typing import Dict, Union

__all__ = ["StatRegistry", "Histogram", "get_histogram", "observe",
           "all_histograms", "reset_all_histograms", "stat_add",
           "stat_sub", "stat_set", "get_stat", "reset_stat", "all_stats",
           "reset_all_stats", "describe", "export_prometheus",
           "snapshot"]

Number = Union[int, float]


class _Stat:
    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, value: Number = 0):
        self.name = name
        self.value = value
        self._lock = threading.Lock()

    def increase(self, v: Number = 1):
        with self._lock:
            self.value += v

    def decrease(self, v: Number = 1):
        with self._lock:
            self.value -= v

    def set(self, v: Number):
        with self._lock:
            self.value = v

    def reset(self):
        with self._lock:
            self.value = 0


class StatRegistry:
    """monitor.h:77 StatRegistry<T>, without the int/float template split —
    python numbers unify both instantiations."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def get(self, name: str) -> _Stat:
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = _Stat(name)
            return s

    def stats(self) -> Dict[str, Number]:
        with self._lock:
            return {n: s.value for n, s in self._stats.items()}

    def reset_all(self):
        with self._lock:
            for s in self._stats.values():
                s.reset()


class Histogram:
    """Fixed-bucket latency/size histogram (the role of brpc's bvar
    LatencyRecorder, reduced to what the PS transport counters need):
    exponential bucket bounds, exact count/sum/max, and interpolated
    percentiles good enough for p50/p95/p99 dashboards.  Thread-safe."""

    # ~exponential bounds; unit-agnostic (the PS transport records ms)
    BOUNDS = (0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0,
              200.0, 500.0, 1000.0, 2000.0, 5000.0, 10000.0)

    def __init__(self, name: str = ""):
        self.name = name
        self._counts = [0] * (len(self.BOUNDS) + 1)
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self._lock = threading.Lock()

    def record(self, value: Number):
        v = float(value)
        i = 0
        for b in self.BOUNDS:
            if v <= b:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            if v > self.max:
                self.max = v

    def reset(self):
        """Zero the histogram IN PLACE — live references (e.g. the
        per-op latency histograms TransportStats holds) keep recording
        into the same registered object."""
        with self._lock:
            self._counts = [0] * (len(self.BOUNDS) + 1)
            self.count = 0
            self.sum = 0.0
            self.max = 0.0

    def percentile(self, p: float) -> float:
        """Linearly interpolated p-quantile: position within the bucket
        holding the quantile, between the bucket's lower and upper
        bounds (0 with no data; ``max`` for the overflow bucket —
        honest about saturation)."""
        with self._lock:
            if not self.count:
                return 0.0
            target = p * self.count
            seen = 0
            for i, c in enumerate(self._counts):
                prev = seen
                seen += c
                if c and seen >= target:
                    if i >= len(self.BOUNDS):
                        return self.max
                    lo = self.BOUNDS[i - 1] if i > 0 else 0.0
                    hi = self.BOUNDS[i]
                    frac = min(1.0, max(0.0, (target - prev) / c))
                    return lo + frac * (hi - lo)
            return self.max

    def buckets(self):
        """Snapshot of (bounds, per-bucket counts incl. the overflow
        slot, total count, sum) — the cumulative-bucket renderer's
        input (export_prometheus)."""
        with self._lock:
            return (list(self.BOUNDS), list(self._counts),
                    self.count, self.sum)

    def summary(self) -> Dict[str, Number]:
        with self._lock:
            count, total, mx = self.count, self.sum, self.max
        return {"count": count, "sum": round(total, 3),
                "mean": round(total / count, 4) if count else 0.0,
                "p50": self.percentile(0.50),
                "p95": self.percentile(0.95),
                "p99": self.percentile(0.99), "max": round(mx, 3)}


_hists: Dict[str, Histogram] = {}
_hist_lock = threading.Lock()


def get_histogram(name: str) -> Histogram:
    with _hist_lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = Histogram(name)
        return h


def observe(name: str, value: Number):
    """Record one observation into the named histogram (histogram
    sibling of :func:`stat_add`)."""
    get_histogram(name).record(value)


def all_histograms() -> Dict[str, Dict[str, Number]]:
    with _hist_lock:
        hs = list(_hists.values())
    return {h.name: h.summary() for h in hs}


def reset_all_histograms():
    """Zero every registered histogram IN PLACE.  Clearing the registry
    dict instead would orphan live references (TransportStats etc.):
    their subsequent records would vanish from :func:`all_histograms`."""
    with _hist_lock:
        hs = list(_hists.values())
    for h in hs:
        h.reset()


def stat_add(name: str, value: Number = 1):
    """STAT_ADD / STAT_INT_ADD / STAT_FLOAT_ADD (monitor.h:135,140)."""
    StatRegistry.instance().get(name).increase(value)


def stat_sub(name: str, value: Number = 1):
    StatRegistry.instance().get(name).decrease(value)


def stat_set(name: str, value: Number):
    """Overwrite the named stat (gauge semantics — e.g. the ingest
    plane's ``input_stall_pct``, recomputed per batch rather than
    accumulated)."""
    StatRegistry.instance().get(name).set(value)


def get_stat(name: str) -> Number:
    return StatRegistry.instance().get(name).value


def reset_stat(name: str):
    StatRegistry.instance().get(name).reset()


def all_stats() -> Dict[str, Number]:
    return StatRegistry.instance().stats()


def reset_all_stats():
    StatRegistry.instance().reset_all()


def snapshot(labels=None) -> Dict[str, dict]:
    """One JSON-able capture of the whole registry: every stat value
    plus every histogram's summary AND raw buckets — the metrics
    snapshot ``tools/health_check.py`` consumes (richer than the
    Prometheus rendering: percentiles come pre-interpolated and the
    bucket arrays survive round-tripping).

    ``labels=`` (an iterable of name prefixes) keeps only stats and
    histograms whose name starts with one of the prefixes — the run
    ledger's capture narrows a huge registry to the series it records
    without a second pass.  ``None`` and an EMPTY iterable both mean
    "no filter" (an empty prefix tuple would otherwise silently drop
    everything — a config that supplies no prefixes wants the default,
    not a blank snapshot).  The ``flight_events`` section (lifetime
    flight-recorder event counts by kind) always rides along, so one
    snapshot call is a complete RunRecord capture."""
    if isinstance(labels, str):
        labels = (labels,)         # a bare string must not filter by
    prefixes = tuple(str(p) for p in labels) if labels is not None \
        else ()                    # its individual characters
    if prefixes:
        def keep(name: str) -> bool:
            return name.startswith(prefixes)
    else:
        def keep(name: str) -> bool:
            return True
    with _hist_lock:
        hs = sorted(_hists.items())
    hists = {}
    for name, h in hs:
        if not keep(name):
            continue
        bounds, counts, count, total = h.buckets()
        rec = h.summary()
        rec["bounds"] = bounds
        rec["bucket_counts"] = counts
        hists[name] = rec
    try:
        # lazy: monitor must stay importable below observability
        from paddle_tpu.framework.observability import flight
        flight_events = flight.kind_totals()
    except Exception:              # noqa: BLE001 — partial-import startup
        flight_events = {}
    return {"stats": {n: v for n, v in all_stats().items() if keep(n)},
            "histograms": hists, "flight_events": flight_events}


# ---------------------------------------------------------------------------
# metrics export (Prometheus exposition text format)
# ---------------------------------------------------------------------------

def _prom_name(name: str) -> str:
    """Sanitize a stat/histogram name into the Prometheus metric-name
    charset ([a-zA-Z_:][a-zA-Z0-9_:]*)."""
    import re
    n = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not n or not re.match(r"[a-zA-Z_:]", n[0]):
        n = "_" + n
    return n


def _prom_num(v: Number) -> str:
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f != f:
        return "NaN"                  # a NaN gauge (numerics on a bad
    if f in (float("inf"), float("-inf")):  # step) must still scrape
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def _split_leaf(name: str):
    """Split a per-leaf stat name — ``base[leaf.path]`` (the numerics
    plane's attribution gauges carry dotted/bracketed pytree paths) —
    into ``(base, leaf)``; ``(name, None)`` for a plain stat."""
    if name.endswith("]") and "[" in name:
        base, leaf = name.split("[", 1)
        return base, leaf[:-1]
    return name, None


def _prom_label_value(v: str) -> str:
    """Escape a label value per the Prometheus exposition grammar
    (backslash, double quote, newline)."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


# metric help texts (# HELP lines): registered by the subsystems that
# own the metric, keyed by the RAW (pre-sanitization) name; metrics
# nobody described get a generated placeholder so a real Prometheus
# scraper (which expects HELP before TYPE) is always satisfied
_help: Dict[str, str] = {}
_help_lock = threading.Lock()


def describe(name: str, help_text: str):
    """Register the ``# HELP`` text for a metric (stat or histogram) —
    one line, no newlines; later registrations win."""
    with _help_lock:
        _help[name] = " ".join(str(help_text).split())


def _help_for(raw_name: str, sanitized: str) -> str:
    with _help_lock:
        text = _help.get(raw_name)
    if text is None:
        text = f"paddle_tpu metric {raw_name}"
    # HELP text escaping per the exposition format: backslash + newline
    return (f"# HELP {sanitized} "
            + text.replace("\\", "\\\\").replace("\n", "\\n"))


def export_prometheus() -> str:
    """Render every registered stat (as a gauge — ``stat_sub`` means
    values may go down) and every histogram (cumulative ``_bucket``
    series + ``_sum``/``_count``) in the Prometheus exposition text
    format, ready for a textfile collector or HTTP scrape handler.

    Every metric gets a ``# HELP`` line before its ``# TYPE`` (text
    from :func:`describe`, or a generated placeholder) — a real
    Prometheus scraper expects the pair.  Names are sanitized into the
    metric-name charset (dots and any other outsider become
    underscores); a per-leaf stat named ``base[leaf.path]`` exports as
    ``base{leaf="leaf.path"}`` — the pytree path survives verbatim in
    the (escaped) label value instead of being mangled into the metric
    name.  ``observability.validate_prometheus`` checks the grammar
    (pass ``require_help=True`` for the full scraper contract); the CI
    observability lane round-trips this output through it."""
    lines = []
    seen = set()
    groups: Dict[str, list] = {}
    raw_names: Dict[str, str] = {}
    for name, v in sorted(all_stats().items()):
        base, leaf = _split_leaf(name)
        n = _prom_name(base)
        raw_names.setdefault(n, base)
        label = None if leaf is None else \
            f'leaf="{_prom_label_value(leaf)}"'
        pairs = groups.setdefault(n, [])
        if any(lab == label for lab, _ in pairs):
            continue                      # sanitization collision: first wins
        pairs.append((label, v))
    for n in sorted(groups):
        seen.add(n)
        lines.append(_help_for(raw_names[n], n))
        lines.append(f"# TYPE {n} gauge")
        for label, v in groups[n]:
            lines.append(f"{n} {_prom_num(v)}" if label is None
                         else f"{n}{{{label}}} {_prom_num(v)}")
    with _hist_lock:
        hs = sorted(_hists.items())
    for name, h in hs:
        n = _prom_name(name)
        if n in seen:
            continue
        seen.add(n)
        bounds, counts, count, total = h.buckets()
        lines.append(_help_for(name, n))
        lines.append(f"# TYPE {n} histogram")
        cum = 0
        for b, c in zip(bounds, counts):
            cum += c
            lines.append(f'{n}_bucket{{le="{_prom_num(b)}"}} {cum}')
        lines.append(f'{n}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{n}_sum {_prom_num(total)}")
        lines.append(f"{n}_count {count}")
    return "\n".join(lines) + "\n"


# core train-loop metrics described where the registry lives; subsystem
# metrics are described by their owning modules via describe()
describe("train_step_ms", "per-step wall time (ms) histogram")
describe("train_steps_total", "train steps completed")
describe("input_stall_pct",
         "share of step time spent waiting on input (gauge)")
describe("collector_pushes_total",
         "telemetry payloads handed to the collector push queue")
describe("collector_dropped_total",
         "telemetry payloads dropped (queue full, dead collector, "
         "injected collector.rpc fault) — never blocks the pusher")
