"""Monitor counters — parity with the reference's StatRegistry
(paddle/fluid/platform/monitor.h:77, STAT_ADD/STAT_SUB macros at
monitor.h:135-141 and the python surface in fluid/core stats).

Process-wide named int/float counters that subsystems bump cheaply and
operators/loggers read for observability (the reference uses them for
e.g. STAT_gpu_mem, sparse table hit rates).  Thread-safe.
"""
from __future__ import annotations

import threading
from typing import Dict, Union

Number = Union[int, float]


class _Stat:
    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, value: Number = 0):
        self.name = name
        self.value = value
        self._lock = threading.Lock()

    def increase(self, v: Number = 1):
        with self._lock:
            self.value += v

    def decrease(self, v: Number = 1):
        with self._lock:
            self.value -= v

    def reset(self):
        with self._lock:
            self.value = 0


class StatRegistry:
    """monitor.h:77 StatRegistry<T>, without the int/float template split —
    python numbers unify both instantiations."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def get(self, name: str) -> _Stat:
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = _Stat(name)
            return s

    def stats(self) -> Dict[str, Number]:
        with self._lock:
            return {n: s.value for n, s in self._stats.items()}

    def reset_all(self):
        with self._lock:
            for s in self._stats.values():
                s.reset()


class Histogram:
    """Fixed-bucket latency/size histogram (the role of brpc's bvar
    LatencyRecorder, reduced to what the PS transport counters need):
    exponential bucket bounds, exact count/sum/max, and interpolated
    percentiles good enough for p50/p95/p99 dashboards.  Thread-safe."""

    # ~exponential bounds; unit-agnostic (the PS transport records ms)
    BOUNDS = (0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0,
              200.0, 500.0, 1000.0, 2000.0, 5000.0, 10000.0)

    def __init__(self, name: str = ""):
        self.name = name
        self._counts = [0] * (len(self.BOUNDS) + 1)
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self._lock = threading.Lock()

    def record(self, value: Number):
        v = float(value)
        i = 0
        for b in self.BOUNDS:
            if v <= b:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            if v > self.max:
                self.max = v

    def percentile(self, p: float) -> float:
        """Upper bucket bound holding the p-quantile (0 with no data;
        ``max`` for the overflow bucket — honest about saturation)."""
        with self._lock:
            if not self.count:
                return 0.0
            target = p * self.count
            seen = 0
            for i, c in enumerate(self._counts):
                seen += c
                if seen >= target:
                    return (self.BOUNDS[i] if i < len(self.BOUNDS)
                            else self.max)
            return self.max

    def summary(self) -> Dict[str, Number]:
        with self._lock:
            count, total, mx = self.count, self.sum, self.max
        return {"count": count, "sum": round(total, 3),
                "mean": round(total / count, 4) if count else 0.0,
                "p50": self.percentile(0.50),
                "p95": self.percentile(0.95),
                "p99": self.percentile(0.99), "max": round(mx, 3)}


_hists: Dict[str, Histogram] = {}
_hist_lock = threading.Lock()


def get_histogram(name: str) -> Histogram:
    with _hist_lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = Histogram(name)
        return h


def observe(name: str, value: Number):
    """Record one observation into the named histogram (histogram
    sibling of :func:`stat_add`)."""
    get_histogram(name).record(value)


def all_histograms() -> Dict[str, Dict[str, Number]]:
    with _hist_lock:
        hs = list(_hists.values())
    return {h.name: h.summary() for h in hs}


def reset_all_histograms():
    with _hist_lock:
        _hists.clear()


def stat_add(name: str, value: Number = 1):
    """STAT_ADD / STAT_INT_ADD / STAT_FLOAT_ADD (monitor.h:135,140)."""
    StatRegistry.instance().get(name).increase(value)


def stat_sub(name: str, value: Number = 1):
    StatRegistry.instance().get(name).decrease(value)


def get_stat(name: str) -> Number:
    return StatRegistry.instance().get(name).value


def reset_stat(name: str):
    StatRegistry.instance().get(name).reset()


def all_stats() -> Dict[str, Number]:
    return StatRegistry.instance().stats()


def reset_all_stats():
    StatRegistry.instance().reset_all()
