"""Persistent run ledger — the observatory's memory.

Every process in this repo already measures itself (tracer spans,
``monitor.snapshot()``, flight events, health anomalies, bench legs) and
then throws the measurement away when it exits: ``BENCH_r*.json`` files
are disconnected snapshots nobody compares, and the GDP-style
auto-tuning loop on the ROADMAP is blocked on exactly the artifact that
never gets built — a queryable history of measured runs.  This module
closes measurement into memory:

* :class:`RunLedger` — a schema-versioned, append-only JSONL store
  (``<FLAGS_runlog_dir>/ledger.jsonl`` by convention).  Appends are
  crash-safe (fcntl lock + O_APPEND + fsync — true appends, so a
  growing history costs O(1) I/O per record, not a full rewrite) and
  independently-launched processes on one host share one ledger;
  readers skip a torn tail instead of crashing
  (``runlog_skipped_records_total``) and tolerate schema-version skew
  (an old reader sees a newer record's known fields and ignores the
  rest).  Ledger I/O faults must never crash the run being recorded:
  every append runs under the ``runlog.observe`` chaos point and
  degrades to a ``runlog.write_error`` flight event + counter.

* :func:`capture` — one call that assembles a :data:`RunRecord`-shaped
  dict from the planes that already exist: run metadata
  (:func:`run_meta` — git sha/dirty, host, FLAGS overrides, versions),
  ``monitor.snapshot()`` (stats + histograms + flight-event kind
  totals), a trace summary (per-span-name aggregates when a trace dir
  is given), and the scalar summary series ``tools/perf_report.py
  compare`` detects regressions over (step-time p99, RPC p99, input
  stall, compile counts, anomaly totals).

Producers in-tree: ``bench.py`` (every completed leg),
``tools/op_bench.py`` (``--ledger``), ``tools/health_check.py
--mini-train`` (``--ledger``), and ``TrainEpochRange`` (when
``FLAGS_runlog_dir`` is set).  ``tools/perf_report.py`` is the
consumer: ``attribute`` joins a merged trace with the PTA106 analytic
cost model, ``compare`` runs ``health.Detector`` over ledger series and
exits nonzero on named regressions.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from paddle_tpu.framework import chaos, locks, monitor
from paddle_tpu.framework.flags import flag

__all__ = ["SCHEMA_VERSION", "LEDGER_NAME", "RunLedger", "run_meta",
           "capture", "default_ledger_path", "bench_record_to_legs",
           "import_bench_file"]

#: bump when the RunRecord shape changes incompatibly; readers must keep
#: accepting records stamped with a DIFFERENT version (known fields are
#: read, unknown fields ignored) — skew degrades, never crashes
SCHEMA_VERSION = 1

LEDGER_NAME = "ledger.jsonl"


def default_ledger_path() -> Optional[str]:
    """``<FLAGS_runlog_dir>/ledger.jsonl``, or None when the flag is
    empty (the implicit producers — TrainEpochRange — stay off)."""
    d = str(flag("runlog_dir") or "")
    if not d:
        return None
    return os.path.join(d, LEDGER_NAME)


# ---------------------------------------------------------------------------
# run metadata (the PR-7 bench metadata, shared)
# ---------------------------------------------------------------------------

_META: Optional[dict] = None
_META_LOCK = locks.lock("runlog.meta")


def run_meta(refresh: bool = False) -> dict:
    """Run metadata stamped into every record, so a regression the
    observatory flags is attributable to the change that caused it:
    git sha (+dirty), host, platform, active FLAGS overrides, versions,
    argv.  The static fields are computed once per process;
    ``flags_overrides`` is re-read every call (a flag flipped after the
    first capture must show in later records).  Every field
    best-effort — metadata must never fail the run it describes."""
    global _META
    with _META_LOCK:
        if _META is not None and not refresh:
            meta = dict(_META)
            try:
                from paddle_tpu.framework import flags as _flags
                meta["flags_overrides"] = _flags.overrides()
            except Exception:      # noqa: BLE001
                pass
            return meta
    import platform
    import socket
    import subprocess
    import sys
    meta: Dict[str, Any] = {
        "host": socket.gethostname(),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "argv": list(sys.argv[1:])}
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        meta["git_sha"] = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo, capture_output=True,
            text=True, timeout=10).stdout.strip() or None
    except Exception:              # noqa: BLE001 — no git, shallow, etc.
        meta["git_sha"] = None
    try:
        # independent of the sha: a slow/failed `git status` must not
        # clobber an already-computed sha
        meta["git_dirty"] = bool(subprocess.run(
            ["git", "status", "--porcelain"], cwd=repo,
            capture_output=True, text=True, timeout=10).stdout.strip())
    except Exception:              # noqa: BLE001
        meta["git_dirty"] = None
    try:
        import jax
        meta["jax"] = jax.__version__
    except Exception:              # noqa: BLE001
        pass
    try:
        from paddle_tpu.framework import flags as _flags
        meta["flags_overrides"] = _flags.overrides()
    except Exception:              # noqa: BLE001
        meta["flags_overrides"] = {}
    with _META_LOCK:
        _META = meta
    return dict(meta)


_RUN_ID: Optional[str] = None


def _run_id() -> str:
    """One id per process, so a multi-leg run's records group.  Minted
    under the meta lock: the id embeds a timestamp, so two racing first
    callers (a TrainEpochRange capture vs a collector capture thread)
    would otherwise mint DIFFERENT ids and split one run's records
    (PTA404)."""
    global _RUN_ID
    with _META_LOCK:
        if _RUN_ID is None:
            _RUN_ID = f"{os.getpid()}-" \
                      f"{int(time.time() * 1e3) & 0xffffffff:x}"
        return _RUN_ID


# ---------------------------------------------------------------------------
# record capture
# ---------------------------------------------------------------------------

def _summary_from_snapshot(snap: dict) -> dict:
    """The per-run scalar series compare detects over, pulled from a
    ``monitor.snapshot()``: histogram p99s for the latency signals,
    counter totals for the rest.  Missing signals are simply absent —
    a record never carries fabricated zeros for planes that were off."""
    stats = snap.get("stats", {})
    hists = snap.get("histograms", {})
    out: Dict[str, float] = {}
    h = hists.get("train_step_ms")
    if h and h.get("count"):
        out["train_step_p99_ms"] = float(h.get("p99", 0.0))
        out["train_step_mean_ms"] = float(h.get("mean", 0.0))
    # client RPC latency lives as per-op histograms
    # (ps_client_rpc_ms_<op>): fold them into one worst-op p99 and a
    # count-weighted mean — the cross-run latency series
    rpc = [h for n, h in hists.items()
           if n.startswith("ps_client_rpc_ms_") and h.get("count")]
    if rpc:
        total = sum(h["count"] for h in rpc)
        out["ps_rpc_p99_ms"] = float(max(h.get("p99", 0.0) for h in rpc))
        out["ps_rpc_mean_ms"] = float(
            sum(h.get("sum", 0.0) for h in rpc) / total) if total else 0.0
    for name in ("input_stall_pct", "jit_compiles_total",
                 "jit_recompiles_steady_total", "health_anomalies_total",
                 "numerics_nonfinite_steps_total", "train_steps_total",
                 "train_nan_skips_total",
                 "zero_collective_bytes_per_step"):
        if name in stats:
            out[name] = float(stats[name])
    return out


def capture(kind: str, label: Optional[str] = None,
            legs: Optional[List[dict]] = None,
            trace_dir: Optional[str] = None,
            labels=None, meta: Optional[dict] = None,
            include_snapshot: bool = True,
            extra: Optional[dict] = None,
            blame_result: Optional[dict] = None) -> dict:
    """Assemble one RunRecord dict (no I/O — pair with
    :meth:`RunLedger.append`).

    ``kind`` names the producer (``bench`` / ``op_bench`` /
    ``health_check`` / ``train_epoch`` / ``imported_bench``); ``label``
    distinguishes variants of one producer (compare only builds series
    within one ``(kind, label)`` group).  ``legs`` are bench-style
    ``{"metric", "value", "unit", ...}`` rows; ``trace_dir`` folds in
    the per-span-name aggregate rows; ``labels=`` narrows the monitor
    snapshot to the given name prefixes (see ``monitor.snapshot``).
    ``include_snapshot=False`` skips the registry snapshot AND the
    derived summary entirely — the shape for a producer that appends
    MANY records per process (bench's per-leg appends): process-
    cumulative counters are only meaningful once per run, and a
    within-run ramp (leg 1 compiled 3 sites, leg 5 has 15) must not
    read as a cross-run regression."""
    if include_snapshot:
        snap = monitor.snapshot(labels=labels)
        summary = _summary_from_snapshot(snap)
        flight_events = snap.pop("flight_events", {})
    else:
        snap, summary = None, {}
        try:
            from paddle_tpu.framework.observability import flight
            flight_events = flight.kind_totals()
        except Exception:          # noqa: BLE001
            flight_events = {}
    rec: Dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "kind": str(kind),
        "label": label,
        "run_id": _run_id(),
        "ts": time.time(),
        "meta": meta if meta is not None else run_meta(),
        "summary": summary,
        "snapshot": snap,
        "flight_events": flight_events,
        "legs": list(legs or []),
    }
    if trace_dir:
        try:
            from paddle_tpu.framework.observability import span_summary
            rows = span_summary(trace_dir)
            if rows:
                rec["trace_summary"] = rows
        except Exception:          # noqa: BLE001 — capture never crashes
            rec["trace_summary"] = None
        try:
            # per-run blame vector (framework/blame.py): the causal
            # critical-path split of the traced steps.  The
            # blame_<cat>_ms per-step means join the summary series so
            # `perf_report compare` can flag a bottleneck SHIFT
            # (compute -> ps_wait at flat step time) cross-run by name.
            # ``blame_result`` short-circuits the trace re-read for a
            # caller that already computed it (health_check's report)
            from paddle_tpu.framework import blame as _blame
            res = blame_result if blame_result is not None else \
                _blame.compute_blame(_blame.load_trace_dir(trace_dir))
            if res.get("n_steps"):
                rec["blame"] = {
                    "n_steps": res["n_steps"],
                    "totals_ms": res["totals_ms"],
                    "per_step_ms": res["per_step_ms"],
                    "shares": res["shares"],
                    "top_category": res["top_category"],
                    "unresolved_links": res["unresolved_links"]}
                rec["summary"].update(_blame.summary(res))
        except Exception:          # noqa: BLE001 — capture never crashes
            pass
    if extra:
        rec.update(extra)
    return rec


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

class RunLedger:
    """Append-only JSONL run store, safe for concurrent writers.

    Appends take an ``fcntl`` lock on ``<path>.lock`` (the
    ``elastic.FileStore`` locking idiom — independently-launched
    processes on one host serialize) and are TRUE appends (O_APPEND +
    flush + fsync): one record costs O(1) I/O however long the history
    grows, where a tmp+rename rewrite would make the cumulative cost
    quadratic.  Crash-safety holds without the rename: a crash
    mid-append can only tear the LAST line, which every reader skips
    (``runlog_skipped_records_total``) and the next append isolates by
    terminating it with a newline first — committed records are never
    touched, one bad line never poisons the history behind it.

    :meth:`append` NEVER raises: ledger I/O faults (proven by the
    ``runlog.observe`` chaos point) degrade to a ``runlog.write_error``
    flight event + ``runlog_write_errors_total`` and return False — the
    run being recorded always survives its recorder."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lockpath = self.path + ".lock"
        self._skipped_seen = 0     # counter dedupe across read passes

    # -- write --------------------------------------------------------------
    def append(self, record: dict) -> bool:
        """Append one record; returns True when it committed.  Failures
        (injected via ``runlog.observe`` or real OS errors) are
        swallowed, counted, and flight-recorded — never raised."""
        try:
            chaos.fault_point("runlog.observe",
                              meta={"op": "append", "path": self.path})
            payload = (json.dumps(record, default=str) + "\n").encode()
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            import fcntl
            with open(self._lockpath, "a+") as lf:
                fcntl.flock(lf.fileno(), fcntl.LOCK_EX)
                try:
                    with open(self.path, "ab") as f:
                        f.seek(0, os.SEEK_END)
                        if f.tell() > 0:
                            # terminate a torn tail so the bad
                            # half-line stays isolated (readers skip
                            # it) instead of swallowing this record
                            # into it
                            with open(self.path, "rb") as rf:
                                rf.seek(-1, os.SEEK_END)
                                torn = rf.read(1) != b"\n"
                            if torn:
                                f.write(b"\n")
                        f.write(payload)
                        f.flush()
                        os.fsync(f.fileno())
                finally:
                    fcntl.flock(lf.fileno(), fcntl.LOCK_UN)
            monitor.stat_add("runlog_records_written_total")
            return True
        except Exception as e:     # noqa: BLE001 — recorder never crashes
            monitor.stat_add("runlog_write_errors_total")
            try:
                from paddle_tpu.framework.observability import flight
                flight.record("runlog.write_error", severity="warn",
                              path=self.path, error=repr(e))
            except Exception:      # noqa: BLE001
                pass
            return False

    # -- read ---------------------------------------------------------------
    def read(self) -> List[dict]:
        """Every parseable record, in append order.  Malformed lines
        (torn tail, hand-edited junk — including a line torn inside a
        multi-byte character: undecodable bytes degrade to replacement
        chars, which JSON rejects, which the skip path absorbs) are
        skipped and counted into ``runlog_skipped_records_total``;
        records from a NEWER schema version are returned as-is
        (consumers read known fields via ``.get`` — skew degrades,
        never crashes).  ``runlog_skipped_records_total`` grows with
        CORRUPTION, not with read frequency: this ledger handle only
        counts skips beyond the most it has already reported."""
        try:
            with open(self.path, encoding="utf-8",
                      errors="replace") as f:
                text = f.read()
        except OSError:
            return []
        records: List[dict] = []
        skipped = 0
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                skipped += 1
                continue
            if not isinstance(rec, dict):
                skipped += 1
                continue
            records.append(rec)
        if skipped > self._skipped_seen:
            monitor.stat_add("runlog_skipped_records_total",
                             skipped - self._skipped_seen)
            self._skipped_seen = skipped
        return records

    def records(self, kind: Optional[str] = None,
                label: Optional[str] = None) -> List[dict]:
        """:meth:`read`, filtered by ``kind`` and/or ``label``."""
        out = self.read()
        if kind is not None:
            out = [r for r in out if r.get("kind") == kind]
        if label is not None:
            out = [r for r in out if r.get("label") == label]
        return out

    def __len__(self) -> int:
        return len(self.read())


# ---------------------------------------------------------------------------
# historical BENCH_r*.json import
# ---------------------------------------------------------------------------

def bench_record_to_legs(text: str) -> List[dict]:
    """Parse bench output lines (one JSON object per line, ``{"metric",
    "value", "unit", "vs_baseline"}``) out of free text — the driver's
    BENCH artifacts keep them inside a captured-stdout ``tail`` that
    also holds warnings and partial lines."""
    legs = []
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec and "value" in rec:
            legs.append(rec)
    return legs


def import_bench_file(path: str) -> Optional[dict]:
    """One historical ``BENCH_r*.json`` driver artifact → one
    ``imported_bench`` RunRecord (None when the file holds no parseable
    bench legs).  The record's ``label`` is ``"BENCH"`` so the imported
    rounds form ONE compare series; ``run`` keeps the round."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(doc, dict):
        legs = bench_record_to_legs(str(doc.get("tail", "")))
        n = doc.get("n")
    else:
        legs, n = [], None
    if not legs:
        return None
    return {
        "schema_version": SCHEMA_VERSION,
        "kind": "imported_bench",
        "label": "BENCH",
        "run_id": os.path.basename(path),
        "run": n,
        "ts": None,
        "meta": {"source": os.path.basename(path)},
        "summary": {},
        "snapshot": None,
        "flight_events": {},
        "legs": legs,
    }
