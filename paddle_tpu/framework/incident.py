"""Postmortem plane — incident capture bundles + deterministic replay.

Every observe/analyze plane in this repo ends at a flight event: a
``train.nan_skip`` names the first bad leaf, a ``parity.divergence``
names the first divergent one, the autopilot records what it actuated —
and then the step's inputs, rng stream, and pre-step state are gone, so
*reproducing* the flagged step means rerunning the whole job.  The
reference's own postmortem story is the same log-line dead end
(FLAGS_check_nan_inf prints and aborts).  This module closes the loop:

* **ring** — with ``FLAGS_incident`` armed, :func:`maybe_note` (hooked
  at the head of ResilientTrainStep / PSTrainStep) keeps the last
  ``FLAGS_incident_ring`` steps of host-copied step inputs (batch
  arrays or PS pulled-row ids), rng state (a pure read — the stream is
  never perturbed), the chaos registry's mid-sequence schedule
  (:func:`chaos.arm_state`), and the pre-step training state.  All
  host-only reads: the armed trajectory is bitwise identical to the
  disarmed one, and disarmed the hook is one flag lookup — no extra
  jit outputs, signature-cache keys byte-identical to the seed.

* **capture** — a subscribed flight kind firing
  (``FLAGS_incident_kinds``; default ``train.nan_skip``,
  ``health.anomaly``, ``numerics.scale_collapse``,
  ``parity.divergence``, ``pallas.divergence``, ``autopilot.action``,
  ``autopilot.revert``) assembles a crash-safe **incident bundle**
  under ``FLAGS_incident_dir``: the input ring, an inline params/opt
  snapshot below ``FLAGS_incident_state_cap_mb`` (or a ``{root,
  generation}`` ref to the newest verified checkpoint generation),
  ``flags.overrides()``, the chaos schedule, ``monitor.snapshot()``,
  the flight tail since the ring began, the blame split when a tracer
  is live, and per-step trajectory hashes (``parity.leaf_hash_host``)
  for first-divergence bisection.  Every file lands tmp+rename with a
  crc32 stamp and the ``COMMIT`` marker is written strictly last —
  :func:`verify_bundle` refuses a torn directory exactly like the
  PR-18 generation walk refuses a torn checkpoint.  The triggering
  event is stamped with the bundle's monotonic ``incident`` id (the
  attr round-trips through ``flight.recent()/since()``), a
  ``kind=incident`` RunLedger record indexes it for ``perf_report
  incidents``, and a bounded notice queue feeds the collector push
  payload.  Capture NEVER raises: the ``incident.capture`` chaos point
  plus a swallow-and-count guard (``incident_capture_errors_total``)
  pin the watcher-never-crashes-the-watched contract.

* **replay** — ``tools/replay.py <bundle>`` re-executes the ring
  standalone: restore the recorded state, re-arm flags + the
  mid-sequence chaos stream, re-feed the ringed inputs through the
  real step surface, and gate that the recorded signal reproduces
  (same ``first_bad_leaf``); ``--bisect`` re-executes with chaos
  DISARMED and walks the recorded trajectory hashes to the first step
  whose clean re-execution diverges — the poisoned step, by number.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from paddle_tpu.framework import chaos, monitor
from paddle_tpu.framework.flags import flag

__all__ = ["DEFAULT_KINDS", "enabled", "subscribed_kinds", "incident_dir",
           "IncidentRecorder", "recorder", "maybe_note", "install",
           "uninstall", "set_program", "reset", "verify_bundle",
           "read_manifest", "load_ring_entry", "state_tree_of_prestate",
           "hash_state_tree", "hash_step_state", "drain_notices",
           "train_surface", "BUNDLE_PREFIX", "MANIFEST_NAME",
           "COMMIT_NAME"]

SCHEMA_VERSION = 1
BUNDLE_PREFIX = "incident_"
MANIFEST_NAME = "manifest.json"
COMMIT_NAME = "COMMIT"
STATE_DIRNAME = "state"

#: the built-in subscription — every plane that names a step/leaf/action
#: worth reproducing offline
DEFAULT_KINDS = ("train.nan_skip", "health.anomaly",
                 "numerics.scale_collapse", "parity.divergence",
                 "pallas.divergence", "autopilot.action",
                 "autopilot.revert")


def enabled() -> bool:
    """True when the postmortem plane is armed (``FLAGS_incident``)."""
    return bool(flag("incident"))


def subscribed_kinds() -> frozenset:
    """Flight kinds that trigger capture (``FLAGS_incident_kinds``,
    comma-separated; empty = :data:`DEFAULT_KINDS`)."""
    raw = str(flag("incident_kinds") or "").strip()
    if not raw:
        return frozenset(DEFAULT_KINDS)
    return frozenset(k.strip() for k in raw.split(",") if k.strip())


def incident_dir() -> str:
    """Bundle root (``FLAGS_incident_dir``; empty = ``incidents`` under
    the current directory)."""
    return str(flag("incident_dir") or "") or os.path.join(
        os.getcwd(), "incidents")


# ---------------------------------------------------------------------------
# state helpers (shared with tools/replay.py)
# ---------------------------------------------------------------------------


def train_surface(step):
    """Unwrap to the innermost object with the TrainStep surface
    (``model``/``optimizer``/``_opt_states``): a ResilientTrainStep
    ring-notes itself, but state capture/restore and trajectory hashing
    happen on the wrapped step."""
    cur = step
    for _ in range(4):
        if getattr(cur, "model", None) is not None:
            return cur
        nxt = getattr(cur, "step", None)
        if nxt is None:
            return cur
        cur = nxt
    return cur


def _host_prestate(step) -> Optional[dict]:
    """Host copy of a TrainStep-surface object's full training state in
    the exact ``_capture_train_state`` shape, so the inline bundle state
    restores through the ordinary ``checkpoint.load_train_state`` path."""
    import jax.tree_util as jtu
    step = train_surface(step)
    model = getattr(step, "model", None)
    opt = getattr(step, "optimizer", None)
    if model is None or opt is None:
        return None
    states = getattr(step, "_opt_states", None)
    return {
        "params": {n: np.asarray(p._data)
                   for n, p in model.named_parameters()},
        "buffers": {n: np.asarray(b._data)
                    for n, b in model.named_buffers() if b is not None},
        "opt_states": jtu.tree_map(np.asarray, states)
        if states is not None else {},
        "global_step": np.int64(getattr(opt, "_global_step", 0)),
    }


def state_tree_of_prestate(pre_state: dict) -> Dict[str, np.ndarray]:
    """Flat name->array view of a :func:`_host_prestate` dict using the
    parity plane's leaf naming (params by name, ``opt<keystr>`` for
    optimizer leaves) — both halves of a bisection name the same leaf."""
    import jax.tree_util as jtu
    tree = dict(pre_state.get("params") or {})
    states = pre_state.get("opt_states")
    if states:
        flat, _ = jtu.tree_flatten_with_path(states)
        for path, leaf in flat:
            if hasattr(leaf, "shape"):
                tree["opt" + jtu.keystr(path)] = leaf
    return tree


def hash_state_tree(tree: Dict[str, Any]) -> Dict[str, int]:
    """Per-leaf host hash of a flat name->array tree
    (:func:`paddle_tpu.parallel.parity.leaf_hash_host`)."""
    from paddle_tpu.parallel.parity import leaf_hash_host
    return {n: leaf_hash_host(tree[n]) for n in sorted(tree)}


def hash_step_state(step) -> Dict[str, int]:
    """Per-leaf host hash of a LIVE step's params + opt-state leaves."""
    from paddle_tpu.parallel.parity import _state_tree
    return hash_state_tree(_state_tree(train_surface(step)))


def _prestate_nbytes(pre_state: dict) -> int:
    import jax.tree_util as jtu
    total = 0
    for leaf in jtu.tree_leaves(pre_state):
        total += getattr(leaf, "nbytes", 0)
    return total


# ---------------------------------------------------------------------------
# the recorder
# ---------------------------------------------------------------------------


class IncidentRecorder:
    """Ring of recent step context + the capture listener.

    One process-wide instance (:data:`recorder`); the ring is rebuilt
    lazily from ``FLAGS_incident_ring`` at first armed note.  All
    mutation happens under one lock; capture itself runs under a
    thread-local reentrancy guard (capture fires flight events — the
    chaos trip, ledger write errors — that must not recurse into a
    second capture)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: Optional[collections.deque] = None
        self._installed = False
        self._tls = threading.local()
        self._program: Optional[dict] = None
        self.notices: collections.deque = collections.deque(maxlen=64)
        self.last_bundle: Optional[str] = None
        self.captured_total = 0

    # -- ring ----------------------------------------------------------------
    def _buf(self) -> collections.deque:
        if self._ring is None:
            self._ring = collections.deque(
                maxlen=max(1, int(flag("incident_ring"))))
        return self._ring

    def note(self, step, inputs) -> None:
        """Record one step's replay context (armed path; callers gate on
        :func:`enabled`).  Host-only reads: input copies, a pure rng
        state read, the chaos schedule, and the pre-step state — the
        watched trajectory is never perturbed."""
        from paddle_tpu.framework.observability import flight
        from paddle_tpu.tensor.random import get_rng_state
        ins = []
        for x in inputs:
            data = getattr(x, "_data", None)
            if data is not None:
                ins.append(("tensor", np.asarray(data)))
            else:
                ins.append(("array", np.asarray(x)))
        surf = train_surface(step)
        entry = {
            "step": int(getattr(getattr(surf, "optimizer", None),
                                "_global_step", 0)),
            "inputs": ins,
            "rng": np.asarray(get_rng_state()),
            "chaos": chaos.arm_state(),
            "flight_seq": flight.last_seq(),
            "pre_state": _host_prestate(step),
            "step_obj": step,
        }
        with self._lock:
            self._buf().append(entry)

    # -- program descriptor --------------------------------------------------
    def set_program(self, builder: str, **kwargs) -> None:
        """Register how a replay rebuilds this process's step surface:
        ``builder`` is a ``"module:function"`` ref returning the step
        object when called with ``**kwargs`` (JSON-able).  Stamped into
        every bundle so ``tools/replay.py`` is standalone."""
        self._program = {"builder": str(builder), "kwargs": dict(kwargs)}

    # -- listener ------------------------------------------------------------
    def install(self) -> None:
        """Subscribe the capture listener to the flight recorder
        (idempotent)."""
        from paddle_tpu.framework.observability import flight
        with self._lock:
            if self._installed:
                return
            self._installed = True
        flight.add_listener(self._on_event)

    def uninstall(self) -> None:
        from paddle_tpu.framework.observability import flight
        with self._lock:
            if not self._installed:
                return
            self._installed = False
        flight.remove_listener(self._on_event)

    def _on_event(self, ev: dict) -> None:
        """The flight listener: subscribed kind → capture a bundle and
        stamp the LIVE event dict with the incident id (the attr
        round-trips through ``recent()/since()``).  NEVER raises."""
        if getattr(self._tls, "in_capture", False):
            return
        try:
            if not enabled() or ev.get("kind") not in subscribed_kinds():
                return
        except Exception:          # noqa: BLE001 — flags gone mid-teardown
            return
        self._tls.in_capture = True
        try:
            chaos.fault_point("incident.capture",
                              meta={"kind": ev.get("kind")})
            bundle = self._capture(ev)
            if bundle is not None:
                ev["attrs"]["incident"] = bundle["incident_id"]
        except Exception:          # noqa: BLE001 — swallow-and-count: the
            # postmortem recorder must never crash the run it records
            monitor.stat_add("incident_capture_errors_total")
        finally:
            self._tls.in_capture = False

    # -- capture -------------------------------------------------------------
    def _claim_bundle_dir(self, root: str):
        """Monotonic incident id from a directory scan, claimed by an
        exclusive mkdir (two racing captures get distinct ids)."""
        os.makedirs(root, exist_ok=True)
        nxt = 1
        for name in os.listdir(root):
            if name.startswith(BUNDLE_PREFIX):
                try:
                    nxt = max(nxt, int(name[len(BUNDLE_PREFIX):]) + 1)
                except ValueError:
                    continue
        for iid in range(nxt, nxt + 1000):
            path = os.path.join(root, f"{BUNDLE_PREFIX}{iid:06d}")
            try:
                os.makedirs(path)
                return iid, path
            except FileExistsError:
                continue
        raise RuntimeError(f"cannot claim an incident dir under {root}")

    def _capture(self, ev: dict) -> Optional[dict]:
        from paddle_tpu.distributed import checkpoint
        from paddle_tpu.framework.observability import flight
        with self._lock:
            entries = list(self._buf())
        iid, path = self._claim_bundle_dir(incident_dir())

        # 1) state: inline below the cap (standalone replay), else a ref
        # to the newest verified checkpoint generation
        state_rec: Dict[str, Any] = {}
        cap_bytes = float(flag("incident_state_cap_mb")) * 1e6
        pre = entries[0]["pre_state"] if entries else None
        if pre is not None and 0 < _prestate_nbytes(pre) <= cap_bytes:
            sdir = os.path.join(path, STATE_DIRNAME)
            checkpoint.save_sharded(pre, sdir,
                                    step=int(pre["global_step"]))
            checkpoint.write_commit(sdir,
                                    generation=int(pre["global_step"]))
            state_rec = {"inline": True, "dir": STATE_DIRNAME}
        else:
            gen_ref = self._generation_ref(entries)
            state_rec = {"inline": False, "ref": gen_ref}

        # 2) the input ring: one crc-stamped .npy per array, tmp+rename
        ring_meta: List[dict] = []
        for i, e in enumerate(entries):
            files = []
            for j, (kind, arr) in enumerate(e["inputs"]):
                fname = f"ring_e{i}_in{j}.npy"
                crc, nbytes = checkpoint._atomic_save(path, fname, arr)
                files.append({"file": fname, "kind": kind,
                              "crc32": crc, "bytes": nbytes})
            rng_f = f"ring_e{i}_rng.npy"
            rng_crc, rng_b = checkpoint._atomic_save(path, rng_f, e["rng"])
            ring_meta.append({
                "step": e["step"], "inputs": files,
                "rng": {"file": rng_f, "crc32": rng_crc, "bytes": rng_b},
                "chaos": e["chaos"], "flight_seq": e["flight_seq"]})

        # 3) trajectory hashes for --bisect: entry i's post-state IS
        # entry i+1's pre-state; the LAST entry's post-state is the live
        # state right now — capture runs inside flight.record, BEFORE
        # any rollback/restore, so it sees the state the signal saw
        trajectory: List[dict] = []
        for i, e in enumerate(entries):
            if e["pre_state"] is None:
                trajectory.append({"step": e["step"], "pre_hashes": None})
            else:
                trajectory.append({
                    "step": e["step"],
                    "pre_hashes": hash_state_tree(
                        state_tree_of_prestate(e["pre_state"]))})
        post_hashes = None
        if entries and entries[-1].get("step_obj") is not None:
            try:
                post_hashes = hash_step_state(entries[-1]["step_obj"])
            except Exception:      # noqa: BLE001 — hash is best-effort
                post_hashes = None

        # 4) manifest (crc-stamped into COMMIT) + COMMIT strictly last
        since = entries[0]["flight_seq"] if entries else 0
        manifest: Dict[str, Any] = {
            "schema_version": SCHEMA_VERSION,
            "incident_id": iid,
            "ts": time.time(),
            "worker": {"pid": os.getpid(),
                       "host": _hostname(),
                       "worker": os.environ.get("PADDLE_TRAINER_ID")},
            "event": {"kind": ev.get("kind"),
                      "severity": ev.get("severity"),
                      "seq": ev.get("seq"),
                      "attrs": _jsonable(ev.get("attrs", {}))},
            "flags_overrides": _flags_overrides(),
            "chaos": entries[0]["chaos"] if entries else chaos.arm_state(),
            "chaos_at_capture": chaos.arm_state(),
            "monitor": _monitor_snapshot(),
            "flight_tail": _jsonable(flight.since(since)),
            "program": self._program,
            "state": state_rec,
            "ring": ring_meta,
            "trajectory": trajectory,
            "post_hashes": post_hashes,
        }
        blame = _blame_window()
        if blame is not None:
            manifest["blame"] = blame
        from paddle_tpu.distributed.fleet.utils.fs import LocalFS
        payload = json.dumps(manifest, default=str)
        LocalFS().atomic_write(os.path.join(path, MANIFEST_NAME), payload)
        LocalFS().atomic_write(
            os.path.join(path, COMMIT_NAME),
            json.dumps({"incident_id": iid, "time": time.time(),
                        "manifest_crc32":
                            zlib.crc32(payload.encode()) & 0xFFFFFFFF}))

        monitor.stat_add("incident_captured_total")
        self.captured_total += 1
        self.last_bundle = path
        notice = {"id": iid, "kind": ev.get("kind"),
                  "step": entries[-1]["step"] if entries else None,
                  "bundle": path,
                  "worker": manifest["worker"]["worker"]
                  or manifest["worker"]["host"]}
        self.notices.append(notice)
        flight.record("incident.captured", severity="info",
                      incident=iid, trigger=ev.get("kind"), bundle=path)
        self._ledger_record(ev, manifest, path)
        return manifest

    def _generation_ref(self, entries) -> Optional[dict]:
        """{root, generation} of the newest verified checkpoint
        generation, when a durable manager is discoverable from the
        ringed step (attach_durable wiring); None otherwise."""
        step = entries[-1].get("step_obj") if entries else None
        mgr = None
        cur = step
        for _ in range(3):
            if cur is None:
                break
            mgr = getattr(cur, "_durable", None)
            if mgr is not None:
                break
            cur = getattr(cur, "step", None)
        if mgr is None:
            return None
        try:
            gen = mgr.latest_verified(deep=False)
        except Exception:          # noqa: BLE001
            return None
        if gen is None:
            return None
        return {"root": os.path.abspath(mgr.root), "generation": int(gen)}

    def _ledger_record(self, ev: dict, manifest: dict, path: str) -> None:
        """kind=incident RunLedger record (best-effort; the ledger's own
        append never raises)."""
        from paddle_tpu.framework import runlog
        lpath = runlog.default_ledger_path()
        if not lpath:
            return
        attrs = manifest["event"].get("attrs") or {}
        info = {"id": manifest["incident_id"],
                "kind": manifest["event"].get("kind"),
                "step": manifest["ring"][-1]["step"]
                if manifest["ring"] else None,
                "first_bad_leaf": attrs.get("first_bad_leaf"),
                "bundle": os.path.abspath(path),
                "worker": manifest["worker"].get("worker")
                or manifest["worker"].get("host")}
        rec = runlog.capture(kind="incident",
                             label=manifest["event"].get("kind"),
                             include_snapshot=False,
                             extra={"incident": info})
        runlog.RunLedger(lpath).append(rec)

    def reset(self) -> None:
        """Clear the ring + notices (tests); the listener stays."""
        with self._lock:
            self._ring = None
            self.notices.clear()
            self.last_bundle = None


def _hostname() -> str:
    import socket
    try:
        return socket.gethostname()
    except Exception:              # noqa: BLE001
        return "unknown"


def _flags_overrides() -> dict:
    from paddle_tpu.framework import flags as _flags
    try:
        return _jsonable(_flags.overrides())
    except Exception:              # noqa: BLE001
        return {}


def _monitor_snapshot() -> Optional[dict]:
    try:
        return _jsonable(monitor.snapshot())
    except Exception:              # noqa: BLE001
        return None


def _blame_window() -> Optional[dict]:
    """Blame split + span window when a tracer is live (FLAGS_trace_dir)
    — best-effort: a torn trace must not fail a capture."""
    try:
        tdir = str(flag("trace_dir") or "")
        if not tdir:
            return None
        from paddle_tpu.framework import blame as _blame
        res = _blame.compute_blame(_blame.load_trace_dir(tdir))
        if not res.get("n_steps"):
            return None
        return {"n_steps": res["n_steps"], "totals_ms": res["totals_ms"],
                "per_step_ms": res["per_step_ms"],
                "top_category": res["top_category"]}
    except Exception:              # noqa: BLE001
        return None


def _jsonable(obj):
    """Round-trip through JSON with default=str so a numpy scalar or an
    exotic attr can never tear the manifest write."""
    return json.loads(json.dumps(obj, default=str))


# ---------------------------------------------------------------------------
# module-level facade
# ---------------------------------------------------------------------------

#: process-wide recorder
recorder = IncidentRecorder()


def maybe_note(step, inputs) -> None:
    """The one-line hook the step classes call at the head of each step:
    one flag lookup when disarmed; armed, ring-record this step's replay
    context and (lazily, once) subscribe the capture listener."""
    if not enabled():
        return
    recorder.install()
    try:
        recorder.note(step, inputs)
    except Exception:              # noqa: BLE001 — swallow-and-count: the
        # ring must never perturb or crash the watched step
        monitor.stat_add("incident_capture_errors_total")


def install() -> None:
    """Subscribe the capture listener without waiting for a first armed
    step — for processes whose subscribed kinds (autopilot.action) can
    fire before any ringed step."""
    recorder.install()


def uninstall() -> None:
    recorder.uninstall()


def set_program(builder: str, **kwargs) -> None:
    """See :meth:`IncidentRecorder.set_program`."""
    recorder.set_program(builder, **kwargs)


def reset() -> None:
    """Clear ring + notices (tests)."""
    recorder.reset()


def drain_notices() -> List[dict]:
    """Incident notices ({id, kind, step, bundle, worker}) accumulated
    since process start, bounded — what the collector client ships in
    its push payload (cumulative, not destructive: a dropped push must
    not lose a notice; the server dedups by id)."""
    return list(recorder.notices)


# ---------------------------------------------------------------------------
# bundle readers (shared with tools/replay.py + tests)
# ---------------------------------------------------------------------------


def verify_bundle(path: str) -> List[dict]:
    """Fsck one bundle directory; ``[]`` = intact.  Mirrors
    ``checkpoint.verify_checkpoint``: a missing/torn COMMIT, a manifest
    whose crc disagrees with the COMMIT stamp, a missing or corrupt ring
    file, or a torn inline state dir each yield a ``{file, reason}``
    problem — replay refuses a bundle with any."""
    problems: List[dict] = []
    commit_path = os.path.join(path, COMMIT_NAME)
    try:
        with open(commit_path) as f:
            commit = json.load(f)
    except (OSError, ValueError):
        return [{"file": COMMIT_NAME, "reason": "missing"}]
    try:
        with open(os.path.join(path, MANIFEST_NAME), "rb") as f:
            raw = f.read()
    except OSError:
        return [{"file": MANIFEST_NAME, "reason": "missing"}]
    want = commit.get("manifest_crc32")
    if want is not None and (zlib.crc32(raw) & 0xFFFFFFFF) != want:
        return [{"file": MANIFEST_NAME, "reason": "crc_mismatch"}]
    try:
        manifest = json.loads(raw.decode("utf-8"))
    except ValueError:
        return [{"file": MANIFEST_NAME, "reason": "bad_manifest"}]
    for e in manifest.get("ring", []):
        for rec in list(e.get("inputs", [])) + [e.get("rng")]:
            if not rec:
                continue
            fp = os.path.join(path, rec["file"])
            try:
                with open(fp, "rb") as f:
                    data = f.read()
            except OSError:
                problems.append({"file": rec["file"], "reason": "missing"})
                continue
            if len(data) != rec.get("bytes"):
                problems.append({"file": rec["file"],
                                 "reason": "truncated"})
            elif (zlib.crc32(data) & 0xFFFFFFFF) != rec.get("crc32"):
                problems.append({"file": rec["file"],
                                 "reason": "crc_mismatch"})
    state = manifest.get("state") or {}
    if state.get("inline"):
        from paddle_tpu.distributed import checkpoint
        sdir = os.path.join(path, state.get("dir") or STATE_DIRNAME)
        if not checkpoint.is_committed(sdir):
            problems.append({"file": state.get("dir") or STATE_DIRNAME,
                             "reason": "state_uncommitted"})
        else:
            problems.extend(checkpoint.verify_checkpoint(sdir, deep=True))
    return problems


def read_manifest(path: str) -> dict:
    with open(os.path.join(path, MANIFEST_NAME)) as f:
        return json.load(f)


def load_ring_entry(path: str, entry: dict) -> dict:
    """Materialize one manifest ring entry: inputs (tensor-kind arrays
    re-wrapped lazily by the caller), rng state, chaos schedule."""
    inputs = []
    for rec in entry.get("inputs", []):
        inputs.append((rec.get("kind", "array"),
                       np.load(os.path.join(path, rec["file"]))))
    rng = np.load(os.path.join(path, entry["rng"]["file"])) \
        if entry.get("rng") else None
    return {"step": entry.get("step"), "inputs": inputs, "rng": rng,
            "chaos": entry.get("chaos")}
