"""Causal critical-path analysis: per-step blame attribution.

Five observability planes can say a step was SLOW; this module says
WHY.  The tracer (PR 5) records spans; PR 15 added causal **links**
across the three async hand-offs (``Span.link`` /
``Tracer.link_next``): PS prefetch task -> consuming step
(``prefetch`` / ``sync_fallback``), ingest fetch task -> consuming step
(``ingest``), deferred coalesced push -> the ``push_pull`` RPC that
carries it (``deferred_push``).  This module reconstructs, per
``train.step`` span, the dependency DAG those edges plus the
parent/child tree define, computes the critical path through the
step's wall-clock **cycle**, and collapses it into a blame vector over
fixed categories:

========== ==========================================================
category   what claims it
========== ==========================================================
compute    unclaimed step time — the chip (or host math) was the path
ps_wait    PS spans (``ps.*``): sync pulls/pushes inside the step,
           linked prefetch tasks whose work ended inside this cycle,
           sync-fallback waits on doomed prefetches
ingest_wait ingest spans (``ingest.*``): linked fetch/transfer tasks
           the step had to wait out
collective spans carrying ``category: "collective"`` (cross-replica
           sync — in-jit collectives have no host span, so this is
           explicit-attr only)
compile    ``jit.compile`` spans (the health plane traces every
           signature-cache miss)
other      any other claiming span (host callbacks, user spans)
========== ==========================================================

**The cycle.**  A step's blame interval runs from the END of the
previous step span on the same lane/thread to this step's end (first
step: its own span).  The inter-step gap is where input waits live —
an ingest stall blocks the loop BETWEEN step spans — so blame over the
bare span would structurally miss the single biggest production
bottleneck (BENCH_r05's 98.98% input stall).  In a tight training loop
the gap is sub-percent, which is why the ``check`` gate can still
demand that categories sum to within tolerance of the measured step
span.

**Claims.**  Synchronous work = the step span's descendants (the
parent/child tree): a ``ps.pull`` issued inside the step blocked it
for its whole interval.  Asynchronous work = linked producers: a
prefetch issued during step N overlaps step N's compute harmlessly;
only the part of it inside step N+1's cycle blocked anything, so
claims are clipped to the cycle.  Producers whose spans outlive their
work (the prefetch span closes at consume time) carry a ``done_ts``
attr marking when the work actually finished — a pull fully hidden
behind the previous step claims ~nothing.  Overlapping claims resolve
by fixed priority (compile > collective > ps_wait > ingest_wait >
other); whatever no claim covers is ``compute``.  The categories
therefore PARTITION the cycle exactly — per-step blame sums to the
cycle length by construction.

Consumers: ``tools/perf_report.py blame`` (report + ``--check`` +
``--expect-top`` CI gates), ``runlog.capture`` (per-run ``blame``
summary -> ``blame_<cat>_ms`` compare series, so a bottleneck SHIFT is
a named cross-run regression even when total step time is flat), and
``tools/health_check.py`` (``--max-blame <cat>=<pct>`` gate).
:func:`publish` exports ``blame_<cat>_ms`` histograms and
``blame_<cat>_pct`` gauges into the monitor registry.
"""
from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional

__all__ = ["CATEGORIES", "LINK_CATEGORY", "categorize",
           "load_trace_dir", "from_chrome_trace", "build_dag",
           "compute_blame", "summary", "publish", "check",
           "format_blame"]

#: the fixed blame vocabulary — compare series, gates and the README
#: table all speak these names
CATEGORIES = ("compute", "ps_wait", "ingest_wait", "collective",
              "compile", "other")

#: claim priority when intervals overlap (a compile inside a pull span
#: is compile); ``compute`` is the unclaimed remainder, never a claim
_PRIORITY = ("compile", "collective", "ps_wait", "ingest_wait", "other")

#: link kind -> category (wins over the producer span's name rule:
#: a sync_fallback edge to a failed prefetch is PS wait whatever the
#: producer was called)
LINK_CATEGORY = {"prefetch": "ps_wait", "sync_fallback": "ps_wait",
                 "deferred_push": "ps_wait", "ingest": "ingest_wait"}


def categorize(name: str, attrs: Optional[dict] = None,
               link_kind: Optional[str] = None) -> str:
    """The blame category a span's time claims.  An explicit
    ``category`` attr wins (the collective hook — in-jit collectives
    have no natural host span name); then the link kind that reached
    it; then the span-name prefix rules."""
    cat = (attrs or {}).get("category")
    if cat in CATEGORIES:
        return str(cat)
    if link_kind is not None and link_kind in LINK_CATEGORY:
        return LINK_CATEGORY[link_kind]
    if name == "jit.compile":
        return "compile"
    if name.startswith("ps."):
        return "ps_wait"
    if name.startswith("ingest."):
        return "ingest_wait"
    if name.startswith(("collective.", "cc.")):
        return "collective"
    if name == "train.step":
        return "compute"
    return "other"


# ---------------------------------------------------------------------------
# span loading (the tracer's own format — no tools/ dependency)
# ---------------------------------------------------------------------------

def _norm(rec: dict, lane: int, shift_us: float) -> Optional[dict]:
    """One tracer span record -> the normalized shape the DAG walk
    uses: clock-corrected start/end (us), identity, links, attrs."""
    try:
        ts = float(rec.get("ts", 0.0)) + shift_us
        dur = float(rec.get("dur", 0.0))
    except (TypeError, ValueError):
        return None
    attrs = dict(rec.get("attrs") or {})
    done = attrs.get("done_ts")
    if isinstance(done, (int, float)):
        # producer-side completion stamp: same process clock as ts,
        # so it takes the same correction
        attrs["done_ts"] = float(done) + shift_us
    return {"id": rec.get("span"), "parent": rec.get("parent"),
            "name": str(rec.get("name", "?")), "ts": ts,
            "end": ts + dur, "dur": dur, "tid": rec.get("tid", 0),
            "lane": lane, "status": rec.get("status", "ok"),
            "attrs": attrs, "links": list(rec.get("links") or ())}


def load_trace_dir(trace_dir: str,
                   label: Optional[str] = None) -> List[dict]:
    """Read every ``trace_*.jsonl`` span file under ``trace_dir`` into
    normalized span dicts, clock-offset corrected onto one timeline
    (the ``trace_merge`` semantics, in-framework — the module that
    writes the format owns its readers).  Malformed lines are skipped,
    torn-trace tolerant."""
    pattern = "trace_*.jsonl" if label is None else \
        f"trace_{label}.jsonl"
    spans: List[dict] = []
    for lane, path in enumerate(sorted(glob.glob(
            os.path.join(trace_dir, pattern)))):
        shift_us = 0.0
        recs = []
        # a rotated previous segment (<path>.1, FLAGS_trace_max_mb) is
        # part of the same logical trace: read it FIRST so a producer
        # span rotated away between its write and its consumer's does
        # not read as a dangling link
        for seg in (path + ".1", path):
            try:
                with open(seg, encoding="utf-8",
                          errors="replace") as f:
                    lines = f.readlines()
            except OSError:
                continue
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                kind = rec.get("kind")
                if kind == "process":
                    try:
                        shift_us = float(
                            rec.get("clock_offset", 0.0)) * 1e6
                    except (TypeError, ValueError):
                        pass
                elif kind == "span":
                    recs.append(rec)
        # the LAST process meta wins (sync_clock re-emits) — apply the
        # final offset to every span of the lane, like trace_merge
        for rec in recs:
            sp = _norm(rec, lane, shift_us)
            if sp is not None:
                spans.append(sp)
    return spans


def from_chrome_trace(trace: dict) -> List[dict]:
    """Normalize a merged chrome-trace dict (``trace_merge.merge``
    output — timestamps already clock-corrected) into the same span
    shape :func:`load_trace_dir` produces, so blame can run on a saved
    merge artifact."""
    offsets = {}
    for f in (trace.get("metadata") or {}).get("files") or ():
        try:
            offsets[int(f.get("lane"))] = \
                float(f.get("clock_offset", 0.0)) * 1e6
        except (TypeError, ValueError):
            pass
    spans = []
    for ev in trace.get("traceEvents", ()):
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args") or {})
        links = args.pop("links", None)
        attrs = {k: v for k, v in args.items()
                 if k not in ("trace", "span", "parent", "status")}
        done = attrs.get("done_ts")
        if isinstance(done, (int, float)):
            # event ts was shifted by merge; the attr was not
            attrs["done_ts"] = float(done) + \
                offsets.get(ev.get("pid"), 0.0)
        ts = float(ev.get("ts", 0.0))
        dur = float(ev.get("dur", 0.0))
        spans.append({"id": args.get("span"), "parent": args.get("parent"),
                      "name": str(ev.get("name", "?")), "ts": ts,
                      "end": ts + dur, "dur": dur,
                      "tid": ev.get("tid", 0),
                      "lane": ev.get("pid", 0),
                      "status": args.get("status", ev.get("cat", "ok")),
                      "attrs": attrs, "links": list(links or ())})
    return spans


# ---------------------------------------------------------------------------
# DAG reconstruction + critical-path blame
# ---------------------------------------------------------------------------

def build_dag(spans: List[dict]) -> dict:
    """Index the span set: ``by_id`` (span id -> span), ``children``
    (parent id -> child spans), and the count of links whose producer
    span is absent (``unresolved_links`` — the integrity number the
    ``--check`` gate demands be zero)."""
    by_id: Dict[str, dict] = {}
    for s in spans:
        if s.get("id") is not None:
            by_id[str(s["id"])] = s
    children: Dict[str, List[dict]] = {}
    unresolved = 0
    for s in spans:
        p = s.get("parent")
        if p is not None:
            children.setdefault(str(p), []).append(s)
        for lk in s.get("links") or ():
            if str(lk.get("span")) not in by_id:
                unresolved += 1
    return {"by_id": by_id, "children": children,
            "unresolved_links": unresolved}


def _producer_end(prod: dict) -> float:
    """When the producer's WORK finished: its ``done_ts`` attr when
    present (spans that stay open until consumed — the prefetch), else
    the span end."""
    done = (prod.get("attrs") or {}).get("done_ts")
    if isinstance(done, (int, float)):
        return min(float(done), prod["end"])
    return prod["end"]


def _step_claims(step: dict, dag: dict) -> List[tuple]:
    """Every (start, end, category, producer_name, edge_kind) interval
    that can claim part of this step's cycle: the step's descendants
    (synchronous work) and its linked producers, recursively through
    THEIR links (visited-guarded, so a malformed cyclic trace cannot
    hang the analysis)."""
    by_id, children = dag["by_id"], dag["children"]
    claims: List[tuple] = []
    seen = {str(step.get("id"))}
    lstack = list(step.get("links") or ())
    stack = list(children.get(str(step.get("id")), ()))
    while stack:
        d = stack.pop()
        did = str(d.get("id"))
        if did in seen:
            continue
        seen.add(did)
        cat = categorize(d["name"], d.get("attrs"))
        if cat != "compute":
            claims.append((d["ts"], d["end"], cat, d["name"], "child"))
        stack.extend(children.get(did, ()))
        # a descendant's own links (e.g. the push_pull RPC's
        # deferred_push edge back to the producing step) join the
        # producer walk — claims clip to the cycle, so a backward edge
        # to a past step claims nothing
        lstack.extend(d.get("links") or ())
    # linked producers (and their links, transitively)
    while lstack:
        lk = lstack.pop()
        prod = by_id.get(str(lk.get("span")))
        if prod is None:
            continue
        pid = str(prod["id"])
        if pid in seen:
            continue
        seen.add(pid)
        kind = lk.get("kind")
        cat = categorize(prod["name"], prod.get("attrs"), link_kind=kind)
        claims.append((prod["ts"], _producer_end(prod), cat,
                       prod["name"], str(kind)))
        lstack.extend(prod.get("links") or ())
    return claims


def compute_blame(spans: List[dict],
                  step_span: str = "train.step") -> dict:
    """Reconstruct the per-step dependency DAG and collapse its
    critical path into per-step blame vectors (see module docstring).
    Returns the full result dict: per-step rows, per-category totals /
    per-step means / shares, the top blocking edges, and the link-
    integrity count."""
    dag = build_dag(spans)
    steps = sorted((s for s in spans if s["name"] == step_span),
                   key=lambda s: (s["lane"], s["tid"], s["ts"]))
    prev_end: Dict[tuple, float] = {}
    step_rows: List[dict] = []
    totals = {c: 0.0 for c in CATEGORIES}
    edge_tot: Dict[tuple, float] = {}
    span_total_us = 0.0
    cycle_total_us = 0.0
    for s in steps:
        key = (s["lane"], s["tid"])
        t0, t1 = s["ts"], s["end"]
        c0 = prev_end.get(key)
        if c0 is None or c0 > t0:
            c0 = t0
        prev_end[key] = t1
        span_total_us += t1 - t0
        cycle_total_us += t1 - c0
        # clip claims to the cycle
        clipped = []
        pts = {c0, t1}
        for (a, b, cat, pname, kind) in _step_claims(s, dag):
            a2, b2 = max(a, c0), min(b, t1)
            if b2 <= a2:
                continue
            clipped.append((a2, b2, cat, pname, kind))
            pts.add(a2)
            pts.add(b2)
        # partition [c0, t1]: boundary sweep, highest-priority claim
        # wins each elementary interval, remainder is compute
        blame_us = {c: 0.0 for c in CATEGORIES}
        bounds = sorted(pts)
        for i in range(len(bounds) - 1):
            a, b = bounds[i], bounds[i + 1]
            if b <= a:
                continue
            winner = None
            for cat in _PRIORITY:
                if any(x <= a and b <= y for (x, y, c, _, _) in clipped
                       if c == cat):
                    winner = cat
                    break
            blame_us[winner or "compute"] += b - a
        for c, v in blame_us.items():
            totals[c] += v
        for (a2, b2, cat, pname, kind) in clipped:
            k = (pname, kind, cat)
            edge_tot[k] = edge_tot.get(k, 0.0) + (b2 - a2)
        step_rows.append({
            "step": len(step_rows), "ts": t0,
            "span_ms": round((t1 - t0) / 1e3, 6),
            "cycle_ms": round((t1 - c0) / 1e3, 6),
            "blame_ms": {c: round(v / 1e3, 6)
                         for c, v in blame_us.items()}})
    n = len(step_rows)
    totals_ms = {c: round(v / 1e3, 6) for c, v in totals.items()}
    per_step_ms = {c: round(v / 1e3 / n, 6) if n else 0.0
                   for c, v in totals.items()}
    total_us = sum(totals.values())
    shares = {c: round(v / total_us, 6) if total_us else 0.0
              for c, v in totals.items()}
    edges = [{"producer": k[0], "kind": k[1], "category": k[2],
              "blocked_ms": round(v / 1e3, 6)}
             for k, v in sorted(edge_tot.items(),
                                key=lambda kv: -kv[1])]
    top = max(shares, key=lambda c: shares[c]) if n else None
    return {"schema_version": 1, "step_span": step_span,
            "n_steps": n, "steps": step_rows,
            "totals_ms": totals_ms, "per_step_ms": per_step_ms,
            "shares": shares, "top_category": top,
            "span_ms_total": round(span_total_us / 1e3, 6),
            "cycle_ms_total": round(cycle_total_us / 1e3, 6),
            "edges": edges[:20],
            "unresolved_links": dag["unresolved_links"]}


# ---------------------------------------------------------------------------
# consumers: summary / publish / gates / rendering
# ---------------------------------------------------------------------------

def summary(result: dict) -> Dict[str, float]:
    """The scalar series a RunRecord carries (``runlog.capture``):
    per-step mean blocked ms per category — the direction-aware
    ``blame_<cat>_ms`` signals ``perf_report compare`` detects
    bottleneck SHIFTS over."""
    return {f"blame_{c}_ms": v
            for c, v in (result.get("per_step_ms") or {}).items()}


def publish(result: dict):
    """Export the blame vectors into the monitor registry: each step's
    per-category ms observed into a ``blame_<cat>_ms`` histogram, the
    run-level share into a ``blame_<cat>_pct`` gauge."""
    from paddle_tpu.framework import monitor
    for row in result.get("steps") or ():
        for c, v in row["blame_ms"].items():
            monitor.observe(f"blame_{c}_ms", float(v))
    for c, v in (result.get("shares") or {}).items():
        monitor.stat_set(f"blame_{c}_pct", round(100.0 * float(v), 4))


def check(result: dict, tolerance: Optional[float] = 0.05,
          expect_top: Optional[str] = None) -> List[str]:
    """The acceptance gates.  Steps-found is always demanded.  With a
    ``tolerance`` (``perf_report blame --check``): every link must
    resolve and the blame categories must sum to within tolerance of
    the measured step span (they sum to the cycle exactly; a cycle far
    off the span means significant wall time lives BETWEEN step spans
    — fine for an input-stalled loop, lying for the back-to-back PS
    acceptance run, which is what this gate pins).  ``tolerance=None``
    skips the sum/integrity gates — the shape for ``--expect-top``
    alone, which must stay usable on exactly the stalled traces whose
    cycle exceeds their span.  ``expect_top`` demands the named
    category carry the largest share — the chaos leg's "injected
    ps.rpc latency must move blame to ps_wait" assertion.  Returns
    violations (empty = pass)."""
    bad = []
    if not result.get("n_steps"):
        bad.append(f"no {result.get('step_span')!r} spans in the trace")
        return bad
    if tolerance is not None:
        if result.get("unresolved_links"):
            bad.append(f"{result['unresolved_links']} unresolved "
                       "link(s): a producer span is missing from the "
                       "trace")
        blame_sum = sum((result.get("totals_ms") or {}).values())
        span_total = float(result.get("span_ms_total") or 0.0)
        if span_total <= 0:
            bad.append("zero total step-span time")
        elif abs(blame_sum - span_total) / span_total > tolerance:
            bad.append(
                f"blame sum {blame_sum:.3f} ms vs step span total "
                f"{span_total:.3f} ms: off by "
                f"{abs(blame_sum - span_total) / span_total:.1%} "
                f"(> {tolerance:.0%})")
    if expect_top is not None and result.get("top_category") != expect_top:
        bad.append(f"top blame category is "
                   f"{result.get('top_category')!r}, expected "
                   f"{expect_top!r} (shares: {result.get('shares')})")
    return bad


def format_blame(result: dict) -> str:
    """Render a blame result as a text report: the per-category table
    and the top blocking edges."""
    lines = [f"== blame ({result['n_steps']} x "
             f"{result['step_span']!r} step(s)) =="]
    if not result["n_steps"]:
        lines.append("no steps found")
        return "\n".join(lines)
    lines.append(
        f"step span total {result['span_ms_total']:.3f} ms, "
        f"cycle total {result['cycle_ms_total']:.3f} ms, "
        f"top category: {result['top_category']}")
    header = ("category", "total_ms", "ms/step", "share")
    table = [header]
    for c in CATEGORIES:
        table.append((c, f"{result['totals_ms'][c]:.3f}",
                      f"{result['per_step_ms'][c]:.3f}",
                      f"{result['shares'][c]:.1%}"))
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    for j, row in enumerate(table):
        lines.append("  ".join(
            cell.ljust(widths[i]) if i == 0 else cell.rjust(widths[i])
            for i, cell in enumerate(row)))
        if j == 0:
            lines.append("  ".join("-" * w for w in widths))
    edges = result.get("edges") or []
    if edges:
        lines.append("-- top blocking edges --")
        for e in edges[:8]:
            lines.append(f"  {e['producer']} [{e['kind']} -> "
                         f"{e['category']}]: {e['blocked_ms']:.3f} ms")
    if result.get("unresolved_links"):
        lines.append(f"UNRESOLVED LINKS: {result['unresolved_links']}")
    return "\n".join(lines)
