"""Typed global flag registry.

One config system replacing the reference's gflags (126 DEFINE_* across
platform/flags.cc etc.) + env-var bootstrap (python/paddle/fluid/__init__.py
__bootstrap__) + runtime get/set (pybind/global_value_getter_setter.cc:330,
surfaced as paddle.set_flags/get_flags).  Flags here are typed, env-seeded
(FLAGS_<name>), and readable/writable at runtime.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict

_registry: Dict[str, Any] = {}
_defaults: Dict[str, Any] = {}
_lock = threading.Lock()


def define_flag(name: str, default, help_str: str = ""):
    env = os.environ.get("FLAGS_" + name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    with _lock:
        _registry[name] = value
        _defaults[name] = default
    return value


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    out = {}
    for n in names:
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _registry:
            raise ValueError(f"unknown flag {n}")
        out[n] = _registry[key]
    return out


def set_flags(flags: dict):
    for n, v in flags.items():
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _registry:
            raise ValueError(f"unknown flag {n}")
        with _lock:
            _registry[key] = v


def flag(name: str):
    return _registry[name]


def overrides() -> Dict[str, Any]:
    """Every flag whose current value differs from its registered
    default — whether env-seeded (FLAGS_<name>) or set at runtime
    (set_flags).  This is what bench.py stamps into its artifact so a
    regression is attributable to the configuration that produced it."""
    with _lock:
        return {n: v for n, v in _registry.items()
                if n in _defaults and v != _defaults[n]}


# the flags the reference exposes that still mean something on TPU
define_flag("check_nan_inf", False,
            "per-op NaN/Inf watcher (ref: FLAGS_check_nan_inf, "
            "framework/details/nan_inf_utils.h)")
define_flag("benchmark", False, "sync + time every op")
define_flag("paddle_num_threads", 1, "host threads for data feeding")
define_flag("use_bf16_matmul", True,
            "allow bf16 matmul accumulation on MXU where AMP is active")
define_flag("cudnn_deterministic", False,
            "accepted for compat; XLA on TPU is deterministic by default")
define_flag("max_inplace_grad_add", 0, "compat no-op")
define_flag("gpt_fused_ce", False,
            "route gpt_loss through the blockwise Pallas linear+softmax-CE "
            "kernel (ops/pallas/fused_ce.py): trades nothing vs XLA on "
            "step time (XLA runs the unfused head at ~MXU peak on v5e) "
            "but eliminates the (B,S,V) f32 logits buffer — enable when "
            "HBM is the binding constraint")
define_flag("eager_op_jit_cache", True,
            "compiled (fwd, vjp) fast path for eager op dispatch, keyed on "
            "op semantics — plays the reference's generated core.ops role "
            "(pybind/op_function_generator.cc).  Cached fns must be pure in "
            "(args, kwargs, closure, defaults): mutable module-level state "
            "read inside an op is frozen at first call.  Disable for impure "
            "custom ops.")
define_flag("conv_workspace_size_limit", 512, "compat no-op")

# fault-tolerance tier (framework/chaos.py + ps/service.py retries):
define_flag("chaos_spec", "",
            "JSON {fault_point: schedule} armed into framework.chaos at "
            "first use — e.g. '{\"ps.rpc\": {\"mode\": \"error\", "
            "\"every\": 3, \"n_times\": 2}}'.  Env form lets the "
            "launcher arm a whole child-process tree; empty = chaos off")
define_flag("chaos_seed", 0,
            "seed for chaos probability schedules (deterministic suites "
            "pin this; the CI chaos lane runs with a fixed seed)")
define_flag("ps_rpc_timeout", 30.0,
            "socket timeout (s) per PS RPC (brpc_ps_client's "
            "rpc_timeout_ms role)")
define_flag("ps_rpc_max_retries", 3,
            "bounded retries per PS RPC before the endpoint is reported "
            "dead to the heartbeat monitor")
define_flag("ps_rpc_backoff_base", 0.05,
            "exponential backoff base (s): sleep base*2^attempt between "
            "PS RPC retries")
define_flag("download_retries", 3,
            "fetch attempts in utils.download before giving up")
define_flag("download_backoff_base", 0.1,
            "exponential backoff base (s) between download fetch retries")

# PS transport tier (ps/service.py wire format + PSTrainStep pipeline):
define_flag("ps_wire_dtype", "bf16",
            "wire encoding for PS pull rows / push grads: 'bf16' "
            "(default, half the f32 bytes, ~3 significant digits), "
            "'int8' (quarter the bytes, per-row scale), 'int4' "
            "(eighth the bytes, two nibbles per byte + per-row "
            "scale), or 'f32' (exact-parity fallback).  Negotiated "
            "per peer: bf16/int8 pulls decode whatever the reply "
            "header declares, int4 pulls and all quantized pushes "
            "engage only after a hello handshake confirms the server "
            "lists the dtype — old/new peers always interoperate at "
            "f32")
define_flag("zero_wire_dtype", "bf16",
            "wire encoding for the ZeRO sharded-update collectives "
            "(parallel/zero.py ShardedUpdateTrainStep reduce-scatter / "
            "all-gather legs): 'bf16' (default, half the f32 bytes), "
            "'int8' (quarter the bytes + one f32 scale per chunk), "
            "'int4' (eighth the bytes, packed nibbles + per-chunk "
            "scale), or 'f32' (exact fallback — trajectory-parity "
            "with the replicated TrainStep, pinned by tests).  "
            "Per-step override via ShardedUpdateTrainStep(wire_dtype=...)")
define_flag("zero_ring_collectives", False,
            "route the dp collective legs through the fused "
            "quantized ring (parallel/ring.py): quant/dequant "
            "overlapped with the neighbor ppermute, per-chunk scales "
            "on the wire.  Applies to ShardedUpdateTrainStep and "
            "CompressedAllReduceTrainStep; the f32 wire stays on the "
            "native XLA collectives (exact leg, bitwise-stable).  "
            "Per-step override via ring=True/False")
define_flag("ps_prefetch_depth", 1,
            "max in-flight prefetched pulls in PSTrainStep's pipeline "
            "(PSTrainStep.prefetch): 0 disables the pipeline, 1 is the "
            "classic double buffer — the next batch's shard fan-out "
            "rides a background executor while the chip runs the "
            "current step, coalesced with the previous step's push "
            "into one RPC round-trip per shard")

# ingest tier (io/pipeline.py streaming data plane):
define_flag("ingest_prefetch_depth", 1,
            "max in-flight batches in IngestPipeline's double buffer "
            "(decode+collate pulled from the loader and device-put on a "
            "background executor while the chip runs the current step); "
            "0 disables the overlap (synchronous fetch+transfer), 1 is "
            "the classic double buffer")
define_flag("ingest_cache_mode", "",
            "decoded-sample cache for epoch >= 2: '' (off), 'memory' "
            "(bounded in-RAM dict), or 'disk' (one crash-safe tmp+rename "
            "file per sample under FLAGS_ingest_cache_dir).  Epoch 1 "
            "records decoded tensors at cache granularity; later epochs "
            "skip JPEG decode entirely on a hit")
define_flag("ingest_cache_dir", "",
            "directory for the disk-backed decoded-sample cache "
            "(ingest_cache_mode='disk'); empty = a 'ingest_cache' dir "
            "under the current directory")
define_flag("ingest_cache_bytes", 1 << 30,
            "byte bound on the decoded-sample cache (memory or disk): "
            "inserts stop once the recorded payload bytes reach the "
            "bound, so a cache can never eat the host")

# observability tier (framework/observability.py + profiler):
define_flag("trace_dir", "",
            "directory for distributed-tracing span files; non-empty "
            "enables the process-wide Tracer, which appends finished "
            "spans to trace_<label>.jsonl there (label from "
            "PADDLE_TRACE_LABEL, set per child by the launcher).  Merge "
            "the per-process files with tools/trace_merge.py")
define_flag("trace_max_mb", 0.0,
            "size cap (MB) per tracer span-file segment: past it the "
            "segment rotates to trace_<label>.jsonl.1 (exactly one "
            "previous segment is kept — a week-long traced run costs "
            "at most 2x the cap on disk) and a fresh segment opens "
            "with a re-emitted process meta record.  Rotations count "
            "into trace_rotations_total, spans lost with an "
            "overwritten .1 segment into trace_spans_dropped_total; "
            "the cluster collector's incremental span cursor detects "
            "the segment change (inode/size) and resets without "
            "double-counting.  0 (default) = unbounded")
define_flag("flight_capacity", 512,
            "flight recorder ring size: the last N structured events "
            "(chaos trips, PS retries, NaN rollbacks, elastic "
            "membership changes) kept for crash dumps and the PS stat "
            "op's 'flight' field")
define_flag("flight_dir", "",
            "directory for flight_<worker>.json crash dumps "
            "(install_crash_handler); empty = current directory")
define_flag("metrics_export_interval", 30.0,
            "seconds between MetricsReporter writes of "
            "monitor.export_prometheus() to its textfile (atomic "
            "tmp+rename, scraper-safe)")
# cluster telemetry tier (framework/collector.py central collector +
# tools/cluster_top.py):
define_flag("collector_endpoint", "",
            "host:port of the central telemetry collector "
            "(framework/collector.py CollectorServer).  Non-empty arms "
            "collector.auto_reporter(): the process pushes periodic "
            "monitor.snapshot() deltas + flight-event deltas over the "
            "PS RPC framing, fire-and-forget (bounded queue, drop "
            "counter, collector.rpc chaos point) — collector loss can "
            "never slow or crash the pushing process.  The launcher "
            "exports it to every child (server AND trainer roles) as "
            "PADDLE_COLLECTOR_ENDPOINT, which takes precedence")
define_flag("collector_interval", 5.0,
            "seconds between telemetry pushes to the collector "
            "(MetricsReporter push mode / collector.auto_reporter)")
define_flag("collector_queue_capacity", 64,
            "bound on the collector push queue: a payload enqueued "
            "while the queue is full is DROPPED and counted "
            "(collector_dropped_total) — the pushing process never "
            "blocks on a slow or dead collector")
define_flag("collector_timeout", 2.0,
            "socket timeout (s) per collector push attempt; a timed-out "
            "push is a drop, never a retry storm")
define_flag("collector_straggler_ratio", 2.0,
            "straggler flag threshold: a worker whose per-interval step "
            "mean exceeds this multiple of the cluster median is named "
            "a straggler in the collector's view / cluster ledger "
            "record (and reported to ElasticAgent.note_stragglers)")
define_flag("ps_hot_row_k", 0,
            "bounded top-k hot-row sketch per host embedding table "
            "(space-saving counters over pulled ids, "
            "device_table.HotRowSketch): the PS stat op and the "
            "collector's cluster view report the k hottest rows per "
            "table — the telemetry a serving/online-learning row cache "
            "needs.  0 (default) disables the sketch: it costs an "
            "np.unique + bounded counter pass on EVERY pull, and "
            "per-step observability work is opt-in in this repo "
            "(FLAGS_numerics precedent); 32 is the recommended "
            "serving-telemetry setting")
# concurrency tier (framework/locks.py runtime lock-order watchdog):
define_flag("lock_watchdog", False,
            "arm the runtime lock-order watchdog: every tracked lock "
            "(locks.lock/locks.rlock — adopted by the PS service, "
            "cluster collector, ingest pipeline, and elastic agent) "
            "records per-thread acquisition order into a global "
            "held-before graph; a cycle fires a locks.cycle flight "
            "event naming the cycle, a hold past "
            "FLAGS_lock_hold_warn_ms fires locks.long_hold, and "
            "lock_waits_total/lock_hold_ms metrics export.  The "
            "watchdog NEVER raises (locks.observe chaos point + "
            "swallow-and-count guard).  Off (default): one flag "
            "lookup per acquire on top of the plain primitive")
define_flag("lock_hold_warn_ms", 1000.0,
            "hold time (ms) past which an armed lock watchdog fires a "
            "locks.long_hold flight event on release; 0 disables the "
            "long-hold check (the hold histogram still records)")
# perf health tier (framework/health.py detectors + compile/memory
# observability):
define_flag("health_detectors", "",
            "streaming anomaly detectors (framework/health.py): "
            "'' = off, 'default' arms the built-in signal set "
            "(train_step_ms, ps_rpc_ms, input_stall_pct, "
            "ps_prefetch_miss), or a JSON object "
            "'{\"signal\": {detector kwargs}}' for a custom set.  Env "
            "form lets a launcher arm a whole child-process tree")
define_flag("health_warmup", 16,
            "baseline samples a health.Detector collects before it "
            "starts scoring (per signal; the warmup absorbs compile "
            "steps and cold caches)")
define_flag("health_z_threshold", 8.0,
            "robust MAD z-score at which a health.Detector flags an "
            "anomaly (per-signal override via the detector spec)")
define_flag("health_compile_warmup_calls", 10,
            "calls per jit site within which recompiles count as "
            "warmup (shape bucketing, lazy first use); a recompile "
            "past this window is steady-state "
            "(jit_recompiles_steady_total) and feeds the "
            "compile-storm detector")
define_flag("health_compile_storm_k", 3,
            "post-warmup recompiles at one jit site that constitute a "
            "compile storm (health.compile_storm flight event)")
define_flag("health_mem_sample_every", 0,
            "sample jax.live_arrays() into device_mem_* gauges every "
            "N train steps (health.MemoryTracker); 0 disables the "
            "per-step hook (sample() stays callable directly)")
# model-numerics tier (framework/numerics.py in-jit tensor stats):
define_flag("numerics", False,
            "arm the model-numerics plane: TrainStep/PSTrainStep/"
            "ShardedUpdateTrainStep compute per-leaf + global grad/param "
            "norms, update ratios, max-abs and non-finite counts INSIDE "
            "the jitted step and publish them as monitor gauges/"
            "histograms + health-detector signals; ResilientTrainStep "
            "switches its finite check to the in-jit aux and stamps "
            "first_bad_leaf into train.nan_skip.  Off (default): the "
            "step traces exactly the disarmed computation — no extra "
            "outputs, no recompile")
define_flag("numerics_sample_every", 10,
            "per-leaf numerics export cadence: the numerics_*[<leaf>] "
            "attribution gauges refresh every Nth published step, and "
            "(when the cadence is > 0) on every non-finite step — the "
            "post-mortem wants the leaf split exactly then.  0 is a "
            "HARD off for the per-leaf export (the metric-cardinality "
            "cap on huge models; NaN provenance still reaches the "
            "flight event), global gauges/histograms still publish "
            "every step")
define_flag("numerics_scale_collapse_k", 4,
            "consecutive GradScaler downscales that constitute a loss-"
            "scale collapse: the amp.GradScaler update path exports its "
            "current scale as the amp_loss_scale gauge and records a "
            "numerics.scale_collapse flight event every K consecutive "
            "decreases (a scale halving K times without an intervening "
            "good streak is a systematic overflow, not a transient)")
# distributed-semantics tier (parallel/parity.py replica-parity probe):
define_flag("replica_parity", False,
            "arm the runtime replica-parity probe: the train-step "
            "classes (TrainStep and its sharded/dp variants) fold a "
            "per-leaf bitwise hash of every fully-replicated multi-"
            "device param/opt-state leaf through a psum-based "
            "agreement check every FLAGS_replica_parity_every steps; "
            "a divergent leaf fires a parity.divergence flight event "
            "naming the first divergent leaf (the same leaf a static "
            "PTA501 finding names) and counts "
            "parity_divergence_total.  The probe NEVER raises "
            "(parity.observe chaos point + swallow-and-count).  Off "
            "(default): one flag lookup per step — the step's own "
            "compiled computation and signature-cache keys are "
            "byte-identical to the probe-less seed")
define_flag("replica_parity_every", 16,
            "replica-parity probe cadence: hash-compare replicated "
            "state every Nth step of each armed train-step object "
            "(the probe is one tiny fused shard_map program; at the "
            "default cadence its cost amortizes below the op_bench "
            "--parity-probe 2% step-time gate)")
# pallas kernel verification tier (ops/pallas/verify.py differential
# oracle — the runtime half of the PTA6xx static passes):
define_flag("pallas_verify", False,
            "arm the Pallas differential oracle: verify_call() runs a "
            "kernel in interpret=True mode against its compiled form "
            "and against the pure-jnp reference on the call's shapes "
            "(flash_autotune additionally sweeps the boundary-shape "
            "corpus per tiling candidate before timing it); a "
            "disagreeing output fires a pallas.divergence flight "
            "event naming the first divergent operand with the SAME "
            "<name>.<operand> label the static PTA6xx pass uses and "
            "counts pallas_divergence_total.  The oracle NEVER raises "
            "(pallas.verify chaos point + swallow-and-count, "
            "pallas_verify_errors_total).  Off (default): one flag "
            "lookup — the kernel callables are not even invoked")
define_flag("pallas_vmem_budget_kb", 16384,
            "analytic VMEM budget (KB) for the static PTA605 pass: "
            "2x the double-buffered in/out block footprints plus "
            "scratch must fit; the 16 MB default is the v5e/v6e "
            "per-core VMEM.  <=0 disables the check")
# continuous-perf observatory (framework/runlog.py + tools/perf_report.py):
define_flag("runlog_dir", "",
            "directory of the persistent run ledger "
            "(<runlog_dir>/ledger.jsonl, append-only JSONL).  Non-empty "
            "arms the implicit producers — TrainEpochRange appends a "
            "RunRecord when an epoch range completes; bench.py and the "
            "tool CLIs (--ledger) take an explicit path and work either "
            "way.  Empty = implicit run recording off")
# autopilot tier (framework/autopilot.py runtime controller +
# tools/autotune.py offline knob search):
define_flag("autopilot", False,
            "arm the runtime autopilot controller "
            "(framework/autopilot.py): telemetry the planes already "
            "publish (health anomalies, blame summaries, straggler "
            "scores, numerics.scale_collapse / train.nan_skip flight "
            "events) maps through the declarative policy table onto "
            "the bounded actuator registry (prefetch depth, wire "
            "dtype, GradScaler growth, snapshot+restore, straggler "
            "shrink).  Off (default): attach() returns None and the "
            "train loop pays one flag lookup")
define_flag("autopilot_dry_run", False,
            "autopilot decisions are logged (flight events + ledger "
            "action records) but NO actuator fires — the trajectory "
            "is bitwise identical to an autopilot-off run")
define_flag("autopilot_interval_steps", 8,
            "steps between autopilot evaluation intervals: tick() is "
            "called per train step, signals are read and policies "
            "evaluated every Nth tick")
define_flag("autopilot_hysteresis", 2,
            "consecutive confirming evaluation intervals before a "
            "policy's action fires (per-policy override in the "
            "policy table); a one-interval blip never actuates")
define_flag("autopilot_cooldown_s", 30.0,
            "per-action cooldown: after an actuator fires (or is "
            "reverted), the same action is suppressed for this many "
            "seconds (injectable clock)")
define_flag("autopilot_max_actions", 4,
            "global action budget: at most this many actions taken "
            "per autopilot_window_s rolling window; excess decisions "
            "are suppressed and recorded (reason='budget')")
define_flag("autopilot_window_s", 300.0,
            "rolling window (s) for the autopilot_max_actions budget")
define_flag("autopilot_rollback_intervals", 1,
            "evaluation intervals after an action before the rollback "
            "guard re-measures its objective (step interval mean + "
            "anomaly/NaN rate) and reverts an action that made "
            "things worse")
define_flag("autopilot_rollback_tolerance", 0.25,
            "relative objective worsening the rollback guard "
            "tolerates before reverting (0.25 = step time may grow "
            "25% before the action is judged harmful; any anomaly/"
            "NaN-rate increase reverts regardless)")
define_flag("autopilot_max_prefetch_depth", 4,
            "ceiling the prefetch.deepen actuator will never push "
            "PSTrainStep.prefetch_depth past")
define_flag("autopilot_straggler_deadline", 60.0,
            "seconds a collector-flagged straggler must stay flagged "
            "(stale-checked) before the elastic.shrink actuator may "
            "invoke ElasticAgent.enforce_straggler_policy")
define_flag("autotune_profile", "",
            "path of a tuned-knob profile JSON emitted by "
            "tools/autotune.py; non-empty makes TrainStep/PSTrainStep/"
            "bench.py apply the profile's knobs (ps_prefetch_depth, "
            "ps_wire_dtype, zero_wire_dtype) via set_flags once per "
            "process at first step construction — the runtime "
            "controller then starts from a tuned operating point.  A "
            "missing/corrupt profile degrades to a counted "
            "autopilot.profile_error flight event, never a crash")
# flight-recorder incident-storm guard (framework/observability.py):
define_flag("flight_storm_window", 1.0,
            "seconds within which identical (kind, attrs) flight "
            "events are deduplicated once flight_storm_k of them "
            "landed — a flapping signal during an incident cannot "
            "wash the bounded ring of its root cause.  Suppressed "
            "events still count into kind_totals and "
            "flight_suppressed_total.  0 disables the guard")
define_flag("flight_storm_k", 8,
            "identical (kind, attrs) flight events tolerated per "
            "flight_storm_window before further identical events are "
            "suppressed (ring skipped, counters still bumped)")
# postmortem tier (framework/incident.py IncidentRecorder +
# tools/replay.py):
define_flag("incident", False,
            "arm the postmortem plane: ResilientTrainStep/PSTrainStep "
            "keep a small host-side ring of recent step inputs (batch "
            "arrays or pulled-row ids, rng state, pre-step training "
            "state, chaos schedule) and a subscribed flight kind "
            "(FLAGS_incident_kinds) firing assembles a crash-safe "
            "incident bundle under FLAGS_incident_dir — checkpoint "
            "generation ref or inline state, the input ring, flags "
            "overrides, monitor snapshot, flight tail — that "
            "tools/replay.py re-executes standalone.  Capture NEVER "
            "raises (incident.capture chaos point + swallow-and-count) "
            "and never perturbs the trajectory (host-only reads).  Off "
            "(default): one flag lookup per step, signature-cache keys "
            "byte-identical to the seed")
define_flag("incident_kinds", "",
            "comma-separated flight kinds that trigger incident "
            "capture; empty = the built-in subscription "
            "(train.nan_skip, health.anomaly, numerics.scale_collapse, "
            "parity.divergence, pallas.divergence, autopilot.action, "
            "autopilot.revert)")
define_flag("incident_dir", "",
            "directory incident bundles land under "
            "(incident_<NNNNNN>/ per capture, monotonic id from a "
            "directory scan); empty = 'incidents' under the current "
            "directory")
define_flag("incident_ring", 4,
            "steps of input history the armed IncidentRecorder keeps "
            "(host copies of step inputs + rng state + pre-step "
            "training state); the bundle replays exactly this window "
            "and --bisect walks it for the first divergent step")
define_flag("incident_state_cap_mb", 64.0,
            "inline-state size cap (MB) per incident bundle: below it "
            "the ring's oldest pre-step params/opt-state snapshot is "
            "embedded in the bundle (standalone replay, no checkpoint "
            "root needed); above it the bundle records a {root, "
            "generation} ref to the newest verified checkpoint "
            "generation instead.  0 forces the generation-ref path")
# durable-state tier (distributed/durable.py CheckpointManager +
# checkpoint.py async save + the SIGTERM emergency-save contract):
define_flag("ckpt_keep_last", 2,
            "checkpoint generations the GC always keeps (newest-first); "
            "the newest VERIFIED commit is kept unconditionally on top "
            "of this, so a bounded retention policy can never delete "
            "the only restorable state")
define_flag("ckpt_keep_every", 0,
            "additionally keep every Nth generation (by generation "
            "number) as a long-horizon archive — 0 disables; e.g. 100 "
            "keeps gen 0, 100, 200, ... forever while ckpt_keep_last "
            "bounds the rest")
define_flag("ckpt_emergency_deadline", 10.0,
            "seconds the SIGTERM emergency save may spend before the "
            "handler gives up and proceeds with the crash dump — the "
            "preemption contract: the save must fit the platform's "
            "grace window, a hung save must not eat it")
define_flag("profiler_max_spans", 100000,
            "cap on retained chrome-trace spans per profiling session; "
            "beyond it spans are dropped (counted — the Profiling "
            "Report and chrome-trace metadata report the drop count) "
            "while the aggregate report keeps counting every event")
