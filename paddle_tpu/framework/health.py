"""Perf health plane: compile & device-memory observability with
streaming anomaly detection.

PR 5 gave the repo raw telemetry — spans, histograms, a flight
recorder — but nothing *watches* it: a recompile storm, an HBM creep,
or a step-time regression stayed invisible until a human read a
chrome-trace.  This module closes measurement into detection (the GDP
loop's missing middle: measure → **detect** → decide), three parts on
one design center (deterministic, clock-injectable, cheap when off):

* **Compile observability** — ``jit.StaticFunction`` / ``TrainStep`` /
  ``PSTrainStep`` report every signature-cache lookup here.  A miss is
  an XLA compile: :func:`note_compile` classifies the *recompile
  cause* by diffing the new signature against the cached ones
  (``new_signature`` / ``shape_change`` / ``dtype_change`` /
  ``static_arg_change``), bumps ``jit_compiles_total`` (+ a per-cause
  counter), records ``compile_ms`` (first-dispatch latency:
  trace+compile+run — the honest proxy without AOT lowering), and
  counts ``jit_recompiles_steady_total`` when a site that already
  compiled recompiles past its warmup calls.  ≥K post-warmup compiles
  at one site is a **compile storm**: a ``health.compile_storm``
  flight-recorder event fires so the post-mortem shows it next to the
  step-time anomalies it caused.  Cache hits land in
  ``jit_cache_hits_total``.

* **Device-memory observability** — :class:`MemoryTracker` samples
  ``jax.live_arrays()`` into ``device_mem_live_bytes`` /
  ``device_mem_peak_bytes`` gauges with per-tag attribution gauges
  (``device_mem_<tag>_bytes``: params / opt state from the
  ``TrainStep`` hook, ingest buffers from ``IngestPipeline``), plus a
  ``health.mem_watermark`` flight event whenever the peak grows by
  ``watermark_frac``.  ``profile(path)`` writes a pprof
  ``device_memory_profile`` when jax provides one.

* **Streaming anomaly detection** — :class:`Detector`: EWMA plus a
  robust MAD z-score over a sliding window, over any monitor stat or
  histogram-fed signal (step time, ``input_stall_pct``, PS RPC
  latency, prefetch miss rate).  Purely value-driven (deterministic —
  the injectable ``clock`` stamps anomalies, it never gates them);
  warmup samples build the baseline, anomalous samples are kept OUT of
  it (a storm must not teach the detector that storms are normal), and
  ``max_consecutive`` anomalies force a re-baseline so a genuine level
  shift is eventually adopted instead of alarming forever.  Anomalies
  feed the FlightRecorder (``health.anomaly``), export as
  ``health_anomalies_total`` / ``health_anomaly_<signal>_total``, and
  ride the PS ``stat`` op (``health`` field) so a worker set can spot
  its straggler.  :meth:`ElasticAgent.arm_hang_deadline
  <paddle_tpu.distributed.elastic.ElasticAgent.arm_hang_deadline>`
  arms the progress watchdog from the measured step-time distribution
  instead of a hardcoded budget.

Arming: ``watch(signal)`` explicitly, or ``FLAGS_health_detectors`` —
``"default"`` arms the built-in signal set (:data:`DEFAULT_SIGNALS`),
a JSON object ``{"signal": {detector kwargs}}`` arms a custom one; the
env form lets a launcher arm a whole child tree.  When nothing is
armed, :func:`observe` is a dict check.  The ``health.detector`` chaos
fault point fires at the head of every observation; an injected error
is swallowed and counted (``health_observe_errors_total``) — detection
must never crash the training loop it watches.

``tools/health_check.py`` renders all of this (plus a trace summary)
as a health report and exits nonzero on tripped detectors — CI and the
future autotuner share one decision surface.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from paddle_tpu.framework import chaos, monitor
from paddle_tpu.framework.flags import flag
from paddle_tpu.framework.observability import flight, tracer

__all__ = ["Anomaly", "Detector", "HealthMonitor", "MemoryTracker",
           "memory", "watch", "observe", "enabled", "snapshot", "reset",
           "classify_recompile", "note_compile", "note_cache_hit",
           "compile_report", "maybe_sample_memory", "DEFAULT_SIGNALS",
           "RECOMPILE_CAUSES"]


# ---------------------------------------------------------------------------
# streaming anomaly detection
# ---------------------------------------------------------------------------

class Anomaly:
    """One flagged observation: the value, its robust z-score, and the
    baseline (window median / MAD scale) it was judged against."""

    __slots__ = ("signal", "value", "z", "median", "scale", "index", "ts")

    def __init__(self, signal: str, value: float, z: float, median: float,
                 scale: float, index: int, ts: float):
        self.signal = signal
        self.value = value
        self.z = z
        self.median = median
        self.scale = scale
        self.index = index
        self.ts = ts

    def to_dict(self) -> dict:
        return {"signal": self.signal, "value": round(self.value, 6),
                "z": round(self.z, 3), "median": round(self.median, 6),
                "scale": round(self.scale, 6), "index": self.index,
                "ts": self.ts}

    def __repr__(self):
        return (f"Anomaly({self.signal}: value={self.value:.4g} "
                f"z={self.z:.1f} median={self.median:.4g})")


class Detector:
    """EWMA + robust MAD z-score over one scalar signal stream.

    Each :meth:`update` folds the value into an EWMA (trend readout)
    and — once ``warmup`` baseline samples exist — scores it against
    the sliding window's median with a MAD scale:
    ``z = 0.6745 * (v - median) / max(MAD, min_mad,
    rel_floor * |median|)``.  The floors keep a near-constant baseline
    (MAD → 0) from flagging benign jitter: on a dead-flat stream only
    a deviation larger than ``rel_floor`` of the level (or ``min_mad``
    absolutely) can trip.  ``|z| >= z_threshold`` flags an
    :class:`Anomaly`.

    Anomalous values never enter the baseline window — a latency storm
    must not teach the detector that storms are normal — but
    ``max_consecutive`` consecutive anomalies force a **re-baseline**
    (window cleared, fresh warmup): a genuine level shift is adopted
    after a bounded alarm burst instead of alarming forever.

    Deterministic: behavior depends only on the value sequence.  The
    injectable ``clock`` (``elastic.DictStore`` discipline) stamps
    anomaly timestamps and never gates detection.
    """

    def __init__(self, signal: str, warmup: Optional[int] = None,
                 window: int = 64, z_threshold: Optional[float] = None,
                 ewma_alpha: float = 0.2, min_mad: float = 1e-9,
                 rel_floor: float = 0.05, max_consecutive: int = 64,
                 clock=None):
        self.signal = signal
        self.warmup = int(flag("health_warmup")) if warmup is None \
            else int(warmup)
        if self.warmup < 4:
            raise ValueError("Detector warmup must be >= 4 samples "
                             "(a 1-sample baseline flags everything)")
        self.window = int(window)
        self.z_threshold = float(flag("health_z_threshold")) \
            if z_threshold is None else float(z_threshold)
        self.ewma_alpha = float(ewma_alpha)
        self.min_mad = float(min_mad)
        self.rel_floor = float(rel_floor)
        self.max_consecutive = int(max_consecutive)
        self.clock = clock or time.time
        self._values: deque = deque(maxlen=self.window)
        self._lock = threading.Lock()    # PS fan-out threads share the
        self._warm_left = self.warmup    # ps_rpc_ms detector
        self.n = 0
        self.anomalies = 0
        self.consecutive = 0
        self.rebaselines = 0
        self.ewma: Optional[float] = None
        self.last: Optional[float] = None
        self.last_z = 0.0

    def update(self, value) -> Optional[Anomaly]:
        """Score one observation; returns the :class:`Anomaly` when it
        trips, else None.  Thread-safe: concurrent feeders (the PS
        client's RPC fan-out threads) serialize on the detector.

        A NON-FINITE observation (a NaN grad norm on a blown-up step)
        is an anomaly by definition — flagged immediately, even during
        warmup, with ``z=inf`` — and never folds into the EWMA or the
        baseline window (one NaN would otherwise poison both
        forever)."""
        v = float(value)
        with self._lock:
            self.n += 1
            self.last = v
            if not np.isfinite(v):
                self.anomalies += 1
                self.consecutive += 1
                # median BEFORE any rebaseline clear: the anomaly must
                # report the baseline it was judged against
                med = float(np.median(np.asarray(self._values,
                                                 np.float64))) \
                    if self._values else 0.0
                if self.consecutive >= self.max_consecutive:
                    self._values.clear()
                    self._warm_left = self.warmup
                    self.consecutive = 0
                    self.rebaselines += 1
                self.last_z = float("inf")
                return Anomaly(self.signal, v, float("inf"), med, 0.0,
                               self.n, self.clock())
            self.ewma = v if self.ewma is None else \
                self.ewma_alpha * v + (1.0 - self.ewma_alpha) * self.ewma
            if self._warm_left > 0:
                self._warm_left -= 1
                self._values.append(v)
                # a clean sample breaks any non-finite anomaly streak
                # even during warmup (the z=inf rule can flag here):
                # isolated NaNs must not ratchet toward a rebaseline
                self.consecutive = 0
                return None
            vals = np.asarray(self._values, np.float64)
            med = float(np.median(vals))
            mad = float(np.median(np.abs(vals - med)))
            scale = max(mad, self.min_mad, self.rel_floor * abs(med))
            z = 0.6745 * (v - med) / scale
            self.last_z = z
            if abs(z) < self.z_threshold:
                self.consecutive = 0
                self._values.append(v)
                return None
            self.anomalies += 1
            self.consecutive += 1
            if self.consecutive >= self.max_consecutive:
                # a sustained shift is the new normal: re-baseline
                # instead of alarming forever (bounded alarm burst by
                # design)
                self._values.clear()
                self._warm_left = self.warmup
                self.consecutive = 0
                self.rebaselines += 1
            return Anomaly(self.signal, v, z, med, scale, self.n,
                           self.clock())

    def last_value(self) -> Optional[float]:
        """Most recent observed value, or ``None`` before the first
        :meth:`observe` — the read half consumers (autopilot policies)
        use instead of reaching into detector internals."""
        with self._lock:
            return self.last

    def baseline(self) -> Optional[float]:
        """Current robust baseline: the rolling-window median the z
        score is computed against, or the EWMA while still warming
        (too few samples for a median), or ``None`` before any data."""
        with self._lock:
            if self._values:
                return float(np.median(np.asarray(self._values,
                                                  np.float64)))
            return self.ewma

    def reset(self) -> None:
        """Forget everything: window, EWMA, warmup progress, and all
        counters — equivalent to a freshly constructed detector.
        Distinct from the automatic rebaseline (which keeps lifetime
        counters); callers use this at deliberate regime changes, e.g.
        after an autopilot action rewrites the knob the signal
        measures."""
        with self._lock:
            self._values.clear()
            self._warm_left = self.warmup
            self.ewma = None
            self.last = None
            self.last_z = 0.0
            self.n = 0
            self.anomalies = 0
            self.consecutive = 0
            self.rebaselines = 0

    def snapshot(self) -> dict:
        with self._lock:
            return {"n": self.n, "anomalies": self.anomalies,
                    "consecutive": self.consecutive,
                    "rebaselines": self.rebaselines,
                    "warming": self._warm_left > 0,
                    "ewma": None if self.ewma is None
                    else round(self.ewma, 6),
                    "last": None if self.last is None
                    else round(self.last, 6),
                    "last_z": round(self.last_z, 3),
                    "z_threshold": self.z_threshold}


#: the built-in signal set FLAGS_health_detectors="default" arms —
#: exactly the streams the train/transport/ingest tiers feed
DEFAULT_SIGNALS: Dict[str, dict] = {
    # per-step wall time (TrainStep / PSTrainStep __call__).  The wide
    # relative floor absorbs host-side dispatch jitter on real (tens
    # of ms+) steps; the absolute ms floor keeps sub-ms CPU baselines
    # from flagging scheduler noise — only a multiple-of-baseline /
    # tens-of-ms step (recompile, stall, storm) trips
    "train_step_ms": {"rel_floor": 0.25, "min_mad": 5.0},
    # client-side PS RPC latency, every op (TransportStats.record);
    # same floor rationale — localhost RPCs are sub-ms and jitter by
    # whole ms under load, a real latency fault is tens of ms
    "ps_rpc_ms": {"rel_floor": 0.25, "min_mad": 5.0},
    # ingest plane consumer stall share (IngestPipeline._note_wait)
    "input_stall_pct": {"min_mad": 1.0},
    # 0/1 stream per consumed prefetch (PSTrainStep._consume_prefetch);
    # the floors make a single post-warmup miss a detectable event on
    # an all-hit baseline without alarming a mixed one
    "ps_prefetch_miss": {"min_mad": 0.05, "z_threshold": 10.0},
    # model-numerics drift signals (framework/numerics.py publish, fed
    # only when FLAGS_numerics arms the in-jit stats; a non-finite
    # value flags instantly via the z=inf rule, and provenance names
    # the leaf).  The wide relative floor absorbs the natural decay of
    # grad norms over a healthy run; a multiple-of-baseline spike (10x
    # grad blow-up, lr accident, loss-scale overflow) trips the step
    # it lands
    "grad_norm": {"rel_floor": 0.5, "min_mad": 1e-9},
    "update_ratio": {"rel_floor": 0.5, "min_mad": 1e-9},
}


class HealthMonitor:
    """Registry of named-signal detectors — the plane's front door.

    ``watch(signal)`` arms a detector (idempotent); ``observe(signal,
    value)`` scores an observation.  Unwatched signals cost a dict
    lookup.  Every anomaly feeds the flight recorder
    (``health.anomaly``) and the monitor counters
    (``health_anomalies_total`` + ``health_anomaly_<signal>_total``).

    The ``health.detector`` chaos fault point fires at the head of
    every observation; an injected error is swallowed and counted
    (``health_observe_errors_total``) — the watcher must never crash
    the training loop it watches (``mode="latency"`` models a slow
    detector the loop simply absorbs).
    """

    def __init__(self, clock=None):
        self.clock = clock
        self._detectors: Dict[str, Detector] = {}
        self._lock = threading.Lock()
        self._checked_flags = False

    # -- arming -------------------------------------------------------------
    def watch(self, signal: str, **detector_kwargs) -> Detector:
        """Arm a detector for ``signal`` (idempotent: an existing
        detector is returned unchanged — re-watching must not wipe a
        live baseline)."""
        with self._lock:
            det = self._detectors.get(signal)
            if det is None:
                if "clock" not in detector_kwargs and \
                        self.clock is not None:
                    detector_kwargs["clock"] = self.clock
                det = self._detectors[signal] = Detector(
                    signal, **detector_kwargs)
            return det

    def arm_from_flags(self, force: bool = False):
        """Arm from ``FLAGS_health_detectors`` (lazy, chaos-style, so a
        launcher arms a whole child tree via the environment):
        ``"default"``/``"1"``/``"auto"`` arms :data:`DEFAULT_SIGNALS`,
        a JSON object ``{"signal": {kwargs}}`` arms a custom set,
        empty leaves the plane off.

        A malformed value (typo'd JSON, unknown detector kwarg) must
        not crash the caller: the arming is lazy, so the first
        :meth:`observe` runs from inside a train step — the
        watcher-never-crashes-watched contract covers config typos
        too.  The error is recorded (``health_config_errors_total`` +
        a ``health.config_error`` flight event) and the plane stays
        off."""
        if self._checked_flags and not force:
            return
        self._checked_flags = True
        raw = str(flag("health_detectors") or "").strip()
        if not raw:
            return
        try:
            if raw.lower() in ("default", "auto", "1", "true"):
                spec: Dict[str, dict] = DEFAULT_SIGNALS
            else:
                spec = json.loads(raw)
            for signal, kw in spec.items():
                self.watch(signal, **dict(kw))
        except Exception as e:          # noqa: BLE001 — config, not code
            monitor.stat_add("health_config_errors_total")
            flight.record("health.config_error", severity="error",
                          flag="health_detectors", value=raw[:200],
                          error=repr(e))

    def detectors(self) -> Dict[str, Detector]:
        with self._lock:
            return dict(self._detectors)

    @property
    def enabled(self) -> bool:
        if not self._checked_flags:
            self.arm_from_flags()
        return bool(self._detectors)

    # -- observation --------------------------------------------------------
    def observe(self, signal: str, value) -> Optional[Anomaly]:
        """Score ``value`` against the ``signal`` detector; no-op (None)
        when the signal is unwatched."""
        if not self._checked_flags:
            self.arm_from_flags()
        try:
            chaos.fault_point("health.detector",
                              meta={"signal": signal})
        except chaos.InjectedFault:
            # the watcher must never crash the watched: swallow, count
            monitor.stat_add("health_observe_errors_total")
            return None
        det = self._detectors.get(signal)
        if det is None:
            return None
        anomaly = det.update(value)
        if anomaly is not None:
            monitor.stat_add("health_anomalies_total")
            monitor.stat_add(f"health_anomaly_{signal}_total")
            flight.record("health.anomaly", severity="warn",
                          signal=signal, value=round(anomaly.value, 6),
                          z=round(anomaly.z, 3),
                          median=round(anomaly.median, 6),
                          index=anomaly.index)
        return anomaly

    # -- introspection ------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able state of every detector plus the compile sites —
        what the PS ``stat`` op's ``health`` field and
        ``tools/health_check.py`` render."""
        dets = self.detectors()
        return {"signals": {s: d.snapshot() for s, d in dets.items()},
                "anomalies_total": sum(d.anomalies for d in dets.values()),
                "compile": compile_report()}

    def reset(self):
        """Drop every detector and pin flag arming off until the next
        explicit :meth:`arm_from_flags` — each test starts here."""
        with self._lock:
            self._detectors.clear()
            self._checked_flags = True


_monitor = HealthMonitor()


def watch(signal: str, **detector_kwargs) -> Detector:
    """Arm a detector on the process-wide health monitor."""
    return _monitor.watch(signal, **detector_kwargs)


def observe(signal: str, value) -> Optional[Anomaly]:
    """Feed one observation to the process-wide health monitor."""
    return _monitor.observe(signal, value)


def enabled() -> bool:
    """True when any detector is armed (flag arming counted)."""
    return _monitor.enabled


def snapshot() -> dict:
    """Process-wide health state (detectors + compile sites)."""
    return _monitor.snapshot()


def reset():
    """Reset detectors, compile sites, and the memory tracker — the
    per-test clean slate (counters in the monitor registry are owned by
    ``monitor.reset_all_stats`` as usual)."""
    _monitor.reset()
    with _sites_lock:
        _sites.clear()
    memory.reset()


# ---------------------------------------------------------------------------
# compile observability
# ---------------------------------------------------------------------------

RECOMPILE_CAUSES = ("new_signature", "shape_change", "dtype_change",
                    "static_arg_change")

_DTYPE_NAMES = ("float", "bfloat", "int", "uint", "bool", "complex")


def _is_dtype_str(v) -> bool:
    if not isinstance(v, str):
        return False
    return v.rstrip("0123456789") in _DTYPE_NAMES


def _sig_diff(a, b, kinds: set) -> bool:
    """Walk two signature trees in parallel, collecting difference
    kinds into ``kinds`` ({"shape", "dtype", "static"}).  Returns False
    when the trees are structurally incomparable (different arity or
    leaf classes) — that is a wholly new signature, not a mutation."""
    if isinstance(a, tuple) and isinstance(b, tuple):
        # a (tag/name, value) pair with equal string heads is a STATIC
        # leaf — ("S", v) from _sig_of, or a to_static (kwarg, value)
        # pair: any value difference, even a tuple of ints that would
        # otherwise read as a shape (e.g. stride=(2,2) -> (2,3)), is a
        # static-arg change, never a phantom shape change
        if len(a) == 2 and len(b) == 2 and isinstance(a[0], str) \
                and isinstance(b[0], str):
            if a[0] != b[0]:
                return False
            if a[1] != b[1]:
                kinds.add("static")
            return True
        # a tuple of ints is a shape; compare it as ONE leaf
        if a != b and all(isinstance(x, int) for x in a) \
                and all(isinstance(x, int) for x in b):
            kinds.add("shape")
            return True
        if len(a) != len(b):
            return False
        return all(_sig_diff(x, y, kinds) for x, y in zip(a, b))
    if isinstance(a, tuple) or isinstance(b, tuple):
        return False
    if a == b:
        return True
    if _is_dtype_str(a) and _is_dtype_str(b):
        kinds.add("dtype")
        return True
    kinds.add("static")
    return True


def classify_recompile(sig, cached_sigs) -> str:
    """Attribute a signature-cache miss to its cause by diffing ``sig``
    against every cached signature and keeping the closest comparable
    one: ``static_arg_change`` > ``dtype_change`` > ``shape_change``
    (a static-arg flip is reported even when it dragged shapes along —
    it is the actionable cause); no comparable cached signature (or an
    empty cache) is a ``new_signature``."""
    best: Optional[set] = None
    for cached in cached_sigs:
        kinds: set = set()
        if not _sig_diff(sig, cached, kinds) or not kinds:
            continue
        if best is None or len(kinds) < len(best):
            best = kinds
    if best is None:
        return "new_signature"
    if "static" in best:
        return "static_arg_change"
    if "dtype" in best:
        return "dtype_change"
    return "shape_change"


class _CompileSite:
    __slots__ = ("name", "calls", "compiles", "steady_recompiles",
                 "causes", "last_cause", "compile_ms_total")

    def __init__(self, name: str):
        self.name = name
        self.calls = 0
        self.compiles = 0
        self.steady_recompiles = 0
        self.causes: Dict[str, int] = {}
        self.last_cause: Optional[str] = None
        self.compile_ms_total = 0.0


_sites: Dict[str, _CompileSite] = {}
_sites_lock = threading.Lock()


def _site(name: str) -> _CompileSite:
    with _sites_lock:
        s = _sites.get(name)
        if s is None:
            s = _sites[name] = _CompileSite(name)
        return s


def note_cache_hit(site: str):
    """A signature-cache hit at ``site`` (one per non-compiling call)."""
    s = _site(site)
    s.calls += 1
    monitor.stat_add("jit_cache_hits_total")


def note_compile(site: str, cause: str, compile_ms: float):
    """A signature-cache miss at ``site``: count the compile under its
    ``cause``, record ``compile_ms``, and run the storm/steady-state
    bookkeeping.  Call sites time the first dispatch of the fresh
    executable (trace + XLA compile + run) and classify the cause with
    :func:`classify_recompile` BEFORE inserting the new signature."""
    if cause not in RECOMPILE_CAUSES:
        cause = "new_signature"
    s = _site(site)
    s.calls += 1
    s.compiles += 1
    s.causes[cause] = s.causes.get(cause, 0) + 1
    s.last_cause = cause
    s.compile_ms_total += float(compile_ms)
    monitor.stat_add("jit_compiles_total")
    monitor.stat_add(f"jit_compiles_{cause}_total")
    monitor.observe("compile_ms", float(compile_ms))
    warmup_calls = int(flag("health_compile_warmup_calls"))
    if s.calls > warmup_calls and s.compiles > 1:
        # a RE-compile past the warmup window: the signature cache was
        # supposed to be settled — count it, and K of them is a storm
        s.steady_recompiles += 1
        monitor.stat_add("jit_recompiles_steady_total")
        storm_k = int(flag("health_compile_storm_k"))
        if s.steady_recompiles >= storm_k and \
                s.steady_recompiles % storm_k == 0:
            flight.record("health.compile_storm", severity="warn",
                          site=site,
                          post_warmup_compiles=s.steady_recompiles,
                          causes=dict(s.causes))


def compile_report() -> Dict[str, dict]:
    """Per-site compile bookkeeping (JSON-able): calls, compiles,
    steady-state recompiles, per-cause counts, total compile ms."""
    with _sites_lock:
        sites = list(_sites.values())
    return {s.name: {"calls": s.calls, "compiles": s.compiles,
                     "steady_recompiles": s.steady_recompiles,
                     "causes": dict(s.causes),
                     "last_cause": s.last_cause,
                     "compile_ms_total": round(s.compile_ms_total, 3)}
            for s in sites}


class _TimedCompile:
    """Context manager the jit tiers wrap a cache-miss dispatch in: a
    ``jit.compile`` tracer span carrying site + cause, timed into
    :func:`note_compile` on exit."""

    __slots__ = ("site", "cause", "_t0", "_span")

    def __init__(self, site: str, cause: str):
        self.site = site
        self.cause = cause
        self._span = None
        self._t0 = 0.0

    def __enter__(self):
        self._span = tracer.start_span(
            "jit.compile", attrs={"site": self.site, "cause": self.cause})
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        ms = (time.perf_counter() - self._t0) * 1e3
        self._span.__exit__(exc_type, exc, tb)
        if exc_type is None:
            note_compile(self.site, self.cause, ms)
        return False


def timed_compile(site: str, cause: Optional[str]):
    """See :class:`_TimedCompile` — the one-liner the jit tiers use.
    ``cause=None`` (a cache hit) returns a no-op context, so a call
    site wraps its dispatch unconditionally instead of duplicating the
    dispatch expression across a compile/hit branch pair."""
    if cause is None:
        return contextlib.nullcontext()
    return _TimedCompile(site, cause)


# ---------------------------------------------------------------------------
# device-memory observability
# ---------------------------------------------------------------------------

class MemoryTracker:
    """Live/peak device-byte gauges over ``jax.live_arrays()`` with
    per-tag attribution.

    :meth:`sample` sums every live jax array's bytes into
    ``device_mem_live_bytes`` (gauge) and tracks the high watermark in
    ``device_mem_peak_bytes``; a peak that grew by at least
    ``watermark_frac`` since the last watermark event records a
    ``health.mem_watermark`` flight event (first nonzero peak counts).
    ``tags`` (e.g. ``{"params": nbytes, "opt_state": nbytes}``) become
    ``device_mem_<tag>_bytes`` gauges — the TrainStep hook attributes
    params/opt state/buffers, the ingest plane its in-flight device
    batches (:meth:`track`).  :meth:`profile` writes jax's pprof
    ``device_memory_profile`` when the installed jax provides one.
    """

    def __init__(self, watermark_frac: float = 0.25, clock=None):
        self.watermark_frac = float(watermark_frac)
        self.clock = clock or time.time
        self.live_bytes = 0
        self.peak_bytes = 0
        self.samples = 0
        self.tags: Dict[str, int] = {}
        self._watermark = 0
        self._lock = threading.Lock()

    def sample(self, tags: Optional[Dict[str, int]] = None) -> dict:
        """One measurement pass; returns ``{"live_bytes", "peak_bytes",
        "tags"}``.  O(#live arrays) metadata walk — no device sync."""
        import jax
        live = 0
        try:
            for a in jax.live_arrays():
                live += int(getattr(a, "nbytes", 0) or 0)
        except Exception:        # noqa: BLE001 — backend without support
            live = 0
        with self._lock:
            self.samples += 1
            self.live_bytes = live
            if live > self.peak_bytes:
                self.peak_bytes = live
            new_watermark = self.peak_bytes > 0 and (
                self._watermark == 0 or self.peak_bytes >=
                self._watermark * (1.0 + self.watermark_frac))
            prev = self._watermark
            if new_watermark:
                self._watermark = self.peak_bytes
            if tags:
                self.tags.update({t: int(b) for t, b in tags.items()})
            tag_snapshot = dict(self.tags)
        monitor.stat_set("device_mem_live_bytes", live)
        monitor.stat_set("device_mem_peak_bytes", self.peak_bytes)
        for t, b in (tags or {}).items():
            monitor.stat_set(f"device_mem_{t}_bytes", int(b))
        if new_watermark:
            flight.record("health.mem_watermark", severity="info",
                          peak_bytes=self.peak_bytes, prev_watermark=prev,
                          tags=tag_snapshot, ts=self.clock())
        return {"live_bytes": live, "peak_bytes": self.peak_bytes,
                "tags": tag_snapshot}

    def track(self, tag: str, nbytes: int):
        """Attribute ``nbytes`` to ``tag`` without a full sample (the
        ingest plane's per-batch hook: metadata-cheap, every batch)."""
        with self._lock:
            self.tags[tag] = int(nbytes)
        monitor.stat_set(f"device_mem_{tag}_bytes", int(nbytes))

    def profile(self, path: str) -> Optional[str]:
        """Write jax's pprof device-memory profile to ``path`` (None
        when the installed jax has no ``device_memory_profile``)."""
        try:
            from jax.profiler import device_memory_profile
        except ImportError:
            return None
        blob = device_memory_profile()
        with open(path, "wb") as f:
            f.write(blob)
        return path

    def snapshot(self) -> dict:
        with self._lock:
            return {"live_bytes": self.live_bytes,
                    "peak_bytes": self.peak_bytes,
                    "samples": self.samples, "tags": dict(self.tags)}

    def reset(self):
        with self._lock:
            self.live_bytes = 0
            self.peak_bytes = 0
            self.samples = 0
            self.tags.clear()
            self._watermark = 0


#: process-wide device-memory tracker (TrainStep / ingest hooks feed it)
memory = MemoryTracker()

_mem_calls = 0
_mem_lock = threading.Lock()


def maybe_sample_memory(tags_fn=None) -> Optional[dict]:
    """The TrainStep hook: sample device memory every
    ``FLAGS_health_mem_sample_every`` calls (0 = off — the default, so
    the per-step cost is one flag read).  ``tags_fn`` is invoked only
    when a sample actually runs."""
    every = int(flag("health_mem_sample_every"))
    if every <= 0:
        return None
    global _mem_calls
    with _mem_lock:
        _mem_calls += 1
        due = _mem_calls % every == 0
    if not due:
        return None
    return memory.sample(tags=tags_fn() if tags_fn is not None else None)
