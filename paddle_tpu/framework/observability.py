"""Unified observability plane: distributed tracing, flight recorder,
metrics export.

The stack already measures itself in islands — ``framework/monitor.py``
counters and histograms, per-link ``TransportStats``, a host-only
profiler — but none of them can follow one request across processes or
answer "what happened right before the crash".  This module is the
missing spine, three tools sharing one design center (cheap when off,
structured when on):

* **Tracer** — trace/span ids layered on the profiler's host spans.
  A :class:`Span` covers one operation; its context (trace id + span
  id) travels inside PS RPC headers (``PsClient`` injects, the server
  re-opens a child span around op handling), so a worker's
  ``push_pull`` and the server work it caused share one trace id.
  Retries reuse the trace id with fresh span ids.  Each process
  appends finished spans to a JSONL file (``FLAGS_trace_dir``);
  ``tools/trace_merge.py`` merges the per-process files into one
  chrome-trace JSON with per-process lanes, correcting clocks with the
  offset measured over the PS ``hello`` handshake
  (:meth:`PsClient.sync_clock`).

* **FlightRecorder** — a bounded, thread-safe ring buffer of
  structured events ``{ts, severity, kind, attrs}`` fed by the
  machinery that matters in a post-mortem: chaos fault firings,
  ``ResilientTrainStep`` NaN skip/restore, elastic
  join/leave/epoch-bump/hang-kill, PS retry/mark_dead/fence-rejection.
  ``recent(n)`` answers live queries (the PS ``stat`` op carries a
  ``flight`` field); :func:`install_crash_handler` dumps
  ``flight_<worker>.json`` on an uncaught exception, and
  ``launch._supervise`` dumps its own recorder when a child fails
  terminally.

* **Metrics export** — :class:`MetricsReporter` renders
  ``monitor.export_prometheus()`` (every stat + histogram, cumulative
  buckets) to a file on an interval, atomically (tmp+rename), so a
  node exporter / sidecar can scrape training metrics without touching
  the process.  :func:`validate_prometheus` checks a rendering against
  the Prometheus text-format grammar (the CI lane's gate).
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from paddle_tpu.framework import locks, monitor
from paddle_tpu.framework.flags import flag

__all__ = ["SpanContext", "Span", "Tracer", "tracer", "FlightRecorder",
           "flight", "MetricsReporter", "install_crash_handler",
           "on_sigterm", "remove_sigterm_callback",
           "validate_prometheus", "span_summary"]


def _new_id() -> str:
    return os.urandom(8).hex()


class SpanContext:
    """What travels across a process boundary: (trace id, span id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanContext({self.trace_id}, {self.span_id})"


class Span:
    """One traced operation.  Context-manager use nests it under the
    thread's current span and ends it on exit; ``detached=True`` spans
    (cross-thread work: a prefetch in flight, a server-side handler)
    are ended explicitly via :meth:`end` and never touch the creating
    thread's stack.

    While profiling is on, entering a span also enters a
    ``profiler.RecordEvent`` of the same name, so traced operations
    appear in the Profiling Report without double instrumentation."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "attrs", "links", "_t0_wall", "_t0_perf", "_ended",
                 "_rec", "status")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 attrs: Optional[dict] = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}
        self.links: List[dict] = []
        self._t0_wall = time.time()
        self._t0_perf = time.perf_counter()
        self._ended = False
        self._rec = None
        self.status = "ok"

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value):
        self.attrs[key] = value

    def link(self, span_id: Optional[str], kind: str = "link"):
        """Record a CAUSAL edge: the work of span ``span_id`` (produced
        on another thread/process — a prefetch task, an ingest fetch, a
        deferred push) was consumed by THIS span.  Parent/child edges
        say "ran inside"; links say "waited for".  ``tools/trace_merge``
        renders links as chrome-trace flow events and
        ``framework/blame.py`` walks them to rebuild the per-step
        dependency DAG.  ``None`` span ids (tracing off at the producer)
        are ignored."""
        if span_id is None:
            return
        self.links.append({"span": str(span_id), "kind": str(kind)})

    def __enter__(self):
        self.tracer._push(self.context())
        from paddle_tpu import profiler
        if profiler.is_profiling():
            self._rec = profiler.RecordEvent(self.name)
            self._rec.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._rec is not None:
            self._rec.__exit__(exc_type, exc, tb)
            self._rec = None
        self.tracer._pop()
        self.end(status="error" if exc_type is not None else self.status,
                 **({"exc": repr(exc)} if exc is not None else {}))
        return False

    def end(self, status: str = "ok", **attrs):
        """Finish the span (idempotent) and append its record to the
        tracer's JSONL file."""
        if self._ended:
            return
        self._ended = True
        self.status = status
        if attrs:
            self.attrs.update(attrs)
        self.tracer._write_span(self)


class _NullSpan:
    """Returned by a disabled tracer: every operation is a no-op and the
    ids are None, so call sites can skip header injection cheaply."""

    trace_id = span_id = parent_id = None
    attrs: dict = {}
    links: tuple = ()
    status = "ok"

    def context(self):
        return None

    def set_attr(self, key, value):
        pass

    def link(self, span_id, kind: str = "link"):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def end(self, status: str = "ok", **attrs):
        pass


_NULL_SPAN = _NullSpan()


class Tracer:
    """Issues trace/span ids and appends finished spans to a JSONL file.

    One module-level singleton (:data:`tracer`) serves normal use —
    enabled via ``FLAGS_trace_dir`` (or :meth:`enable`), labeled via
    ``PADDLE_TRACE_LABEL`` (the launcher sets it per child).  Separate
    instances may be constructed for in-process multi-role tests (one
    file per logical "process") and handed to ``PsServer``/``PsClient``.

    Span file format — one JSON object per line:

    * ``{"kind": "process", "label", "pid", "clock_offset"}`` — emitted
      on open and again whenever :meth:`set_clock_offset` runs;
      ``clock_offset`` (seconds) is what ``trace_merge`` ADDS to this
      file's timestamps to land them on the reference clock.
    * ``{"kind": "span", "name", "trace", "span", "parent", "ts",
      "dur", "status", "tid", "attrs"}`` — ``ts`` epoch microseconds,
      ``dur`` microseconds; spans with causal links additionally carry
      ``"links": [{"span": <producer span id>, "kind": <edge kind>}]``
      (see :meth:`Span.link` / :meth:`link_next` — rendered as
      chrome-trace flow events by ``tools/trace_merge.py`` and walked
      by ``framework/blame.py``).

    ``FLAGS_trace_max_mb`` > 0 bounds segment growth: a full segment
    rotates to ``<path>.1`` (one kept) and a fresh one opens — see
    :meth:`_rotate_locked`.
    """

    def __init__(self, trace_dir: Optional[str] = None,
                 label: Optional[str] = None):
        self._dir = trace_dir
        self.label = label or os.environ.get(
            "PADDLE_TRACE_LABEL") or f"pid{os.getpid()}"
        self._file = None
        self._file_lock = locks.lock("obs.tracer.file")
        self._local = threading.local()
        self._checked_env = trace_dir is not None
        self.clock_offset = 0.0
        self.spans_written = 0
        # -- segment rotation (FLAGS_trace_max_mb): bound span-file
        # growth.  When the current segment exceeds the cap it is
        # renamed to <path>.1 (overwriting — at most TWO segments ever
        # exist, so a week-long traced run costs 2x the cap, not the
        # disk) and a fresh segment opens with a re-emitted process
        # meta record.  Rotations and the spans lost with an
        # overwritten .1 segment are counted (trace_rotations_total /
        # trace_spans_dropped_total)
        self.rotations = 0
        self.spans_dropped = 0
        self._segment_spans = 0      # spans in the current segment
        self._rotated_spans = 0      # spans sitting in the .1 segment

    # -- enablement ---------------------------------------------------------
    @property
    def enabled(self) -> bool:
        if not self._checked_env:  # pta: disable=PTA404 (idempotent env re-read: racing arm-from-env passes compute identical values, and span writes re-check under _file_lock)
            # lazy env arming, chaos-style: a launcher can turn tracing
            # on for a whole child tree via FLAGS_trace_dir alone
            self._checked_env = True
            d = flag("trace_dir")
            if d:
                self._dir = str(d)
        return bool(self._dir)

    def enable(self, trace_dir: str, label: Optional[str] = None):
        with self._file_lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            self._dir = trace_dir
            self._checked_env = True
            if label:
                self.label = label
            # fresh target: the per-segment rotation accounting belongs
            # to the previous dir/label — carrying it over would charge
            # phantom drops against the new trace's first rotation
            self._segment_spans = 0
            self._rotated_spans = 0
        return self

    def disable(self):
        with self._file_lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            self._dir = None
            self._checked_env = True
            self._segment_spans = 0
            self._rotated_spans = 0

    def path(self) -> Optional[str]:
        """The span file this tracer appends to (None when disabled)."""
        if not self.enabled:
            return None
        return os.path.join(self._dir, f"trace_{self.label}.jsonl")

    # -- thread-local context stack -----------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, ctx: SpanContext):
        self._stack().append(ctx)

    def _pop(self):
        st = self._stack()
        if st:
            st.pop()

    def current(self) -> Optional[SpanContext]:
        st = self._stack()
        return st[-1] if st else None

    def activate(self, ctx: Optional[SpanContext]):
        """Adopt a foreign span context on THIS thread (background
        executors: the prefetch task runs under the span opened at
        issue time, so its RPCs parent correctly).  ``None`` is a
        no-op."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            if ctx is None:
                yield
                return
            self._push(ctx)
            try:
                yield
            finally:
                self._pop()
        return cm()

    # -- causal links across async boundaries -------------------------------
    _PENDING_CAP = 16

    def link_next(self, span_id: Optional[str], kind: str):
        """Declare that the NEXT consuming span started on this thread
        causally depends on producer span ``span_id`` — the hand-off
        idiom for code that releases work to a consumer it cannot see
        (the ingest pipeline yielding a prefetched batch to whatever
        train step runs next; code that hands work across an executor
        it does not own passes ``links=`` explicitly instead — see
        ``PsClient._rpc``).  Pending declarations attach to the next
        :meth:`start_span` on this thread whose ``consume_links`` is
        true (detached producer spans and the pipeline's own internal
        spans skip them); the list is bounded — a consumer that never
        opens a span cannot leak links without bound."""
        if span_id is None or not self.enabled:
            return
        pending = getattr(self._local, "pending", None)
        if pending is None:
            pending = self._local.pending = []
        pending.append({"span": str(span_id), "kind": str(kind)})
        del pending[:-self._PENDING_CAP]

    def _take_pending_links(self) -> List[dict]:
        pending = getattr(self._local, "pending", None)
        if not pending:
            return []
        out, pending[:] = list(pending), []
        return out

    # -- span creation ------------------------------------------------------
    def start_span(self, name: str, parent=None, attrs: Optional[dict] = None,
                   detached: bool = False,
                   consume_links: bool = True) -> Span:
        """New span under ``parent`` (a Span, SpanContext, or None for
        the thread's current span; a fresh trace when there is none).
        Context-manager use ends it automatically; ``detached=True``
        spans are ended explicitly with :meth:`Span.end`.  A
        non-detached span with ``consume_links`` (the default) adopts
        this thread's pending :meth:`link_next` declarations as causal
        links; producers pass ``consume_links=False`` so a hand-off
        waiting for its consumer is not swallowed by infrastructure
        spans."""
        if not self.enabled:
            return _NULL_SPAN
        if isinstance(parent, Span):
            parent = parent.context()
        if parent is None:
            parent = self.current()
        if parent is None:
            trace_id, parent_id = _new_id(), None
        else:
            trace_id, parent_id = parent.trace_id, parent.span_id
        span = Span(self, name, trace_id, _new_id(), parent_id, attrs)
        if not detached and consume_links:
            span.links.extend(self._take_pending_links())
        return span

    # -- wire propagation ---------------------------------------------------
    def inject(self, header: dict, span: Optional[Span] = None) -> dict:
        """Stamp ``header`` with the span's (or current) context."""
        ctx = span.context() if isinstance(span, Span) else self.current()
        if ctx is not None:
            header["trace"] = ctx.trace_id
            header["span"] = ctx.span_id
        return header

    @staticmethod
    def extract(header: dict) -> Optional[SpanContext]:
        t, s = header.get("trace"), header.get("span")
        if t is None or s is None:
            return None
        return SpanContext(str(t), str(s))

    # -- clock correction ---------------------------------------------------
    def set_clock_offset(self, offset: float):
        """Record the measured offset to the reference clock (seconds to
        ADD to this process's timestamps); re-emits the process meta
        record so the merge uses the freshest measurement."""
        self.clock_offset = float(offset)
        if self.enabled:
            self._write(self._meta_record())

    # -- file plumbing ------------------------------------------------------
    def _meta_record(self) -> dict:
        return {"kind": "process", "label": self.label, "pid": os.getpid(),
                "clock_offset": self.clock_offset}

    def _write(self, record: dict):
        with self._file_lock:
            if self._dir is None:
                # disabled (possibly since the span started): a detached
                # span draining after shutdown drops its record instead
                # of crashing the training/serving path
                return
            if self._file is None:
                os.makedirs(self._dir, exist_ok=True)
                fresh = not os.path.exists(self.path())
                self._file = open(self.path(), "a")
                if fresh or os.path.getsize(self.path()) == 0:
                    self._file.write(json.dumps(self._meta_record()) + "\n")
            self._file.write(json.dumps(record, default=str) + "\n")
            self._file.flush()
            if record.get("kind") == "span":
                self._segment_spans += 1
            max_mb = float(flag("trace_max_mb"))
            if max_mb > 0 and self._file.tell() > max_mb * (1 << 20):
                self._rotate_locked()

    def _rotate_locked(self):
        """Roll the full current segment aside as ``<path>.1`` (one
        previous segment is kept; an older one is overwritten and its
        spans counted dropped) and open a fresh segment on the next
        write.  Called under ``_file_lock``."""
        self._file.close()
        self._file = None
        path = self.path()
        dropped = self._rotated_spans
        try:
            os.replace(path, path + ".1")
        except OSError:
            return                  # rotation is best-effort: keep tracing
        self._rotated_spans = self._segment_spans
        self._segment_spans = 0
        self.rotations += 1
        monitor.stat_add("trace_rotations_total")
        if dropped:
            self.spans_dropped += dropped
            monitor.stat_add("trace_spans_dropped_total", dropped)

    def _write_span(self, span: Span):
        dur = time.perf_counter() - span._t0_perf
        rec = {
            "kind": "span", "name": span.name, "trace": span.trace_id,
            "span": span.span_id, "parent": span.parent_id,
            "ts": span._t0_wall * 1e6, "dur": dur * 1e6,
            "status": span.status, "tid": threading.get_ident(),
            "attrs": span.attrs}
        if span.links:
            rec["links"] = list(span.links)
        self._write(rec)
        self.spans_written += 1


#: process-wide default tracer (FLAGS_trace_dir / PADDLE_TRACE_LABEL)
tracer = Tracer()


def span_summary(trace_dir: str, label: Optional[str] = None) -> List[dict]:
    """Per-span-name aggregates over every ``trace_*.jsonl`` file under
    ``trace_dir`` — count, total/mean/p99/max ms, error count — sorted
    heaviest-first.  ``label=`` restricts the summary to ONE process's
    span file (``trace_<label>.jsonl``) — the single-process view of a
    shared trace dir (the cluster collector's push path keeps its own
    incremental reader, ``collector._own_span_rows``, for the same
    file).  This reads the Tracer's OWN span-file format (the
    module that writes it owns the reader), so in-framework consumers
    (the run ledger's RunRecord capture) need no dependency on
    ``tools/trace_merge.py``; that tool renders the same shape from a
    merged chrome-trace.  Durations need no clock correction — offsets
    shift timestamps, not spans' lengths.  Malformed lines are skipped,
    torn-trace tolerant."""
    import glob

    durs: Dict[str, List[float]] = {}
    errors: Dict[str, int] = {}
    categories: Dict[str, str] = {}
    pattern = "trace_*.jsonl" if label is None else f"trace_{label}.jsonl"
    seg_paths = []
    for path in sorted(glob.glob(os.path.join(trace_dir, pattern))):
        # a rotated previous segment is the same logical trace
        seg_paths += [path + ".1", path]
    for path in seg_paths:
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") != "span":
                continue
            name = str(rec.get("name", "?"))
            durs.setdefault(name, []).append(
                float(rec.get("dur", 0.0)) / 1e3)
            if rec.get("status") == "error":
                errors[name] = errors.get(name, 0) + 1
            cat = (rec.get("attrs") or {}).get("category")
            if cat is not None and name not in categories:
                categories[name] = str(cat)
    rows = []
    for name, ms in durs.items():
        ms.sort()
        n = len(ms)
        # single-sample group: the p99 IS that sample (the general
        # nearest-rank formula agrees, but the contract is explicit —
        # blame tooling consumes these rows)
        p99 = ms[0] if n == 1 else \
            ms[min(n - 1, max(0, int(0.99 * n + 0.5) - 1))]
        row = {"name": name, "count": n,
               "total_ms": round(sum(ms), 3),
               "mean_ms": round(sum(ms) / n, 3),
               "p99_ms": round(p99, 3),
               "max_ms": round(ms[-1], 3),
               "errors": errors.get(name, 0)}
        if name in categories:
            row["category"] = categories[name]
        rows.append(row)
    rows.sort(key=lambda r: r["total_ms"], reverse=True)
    return rows


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

_SEVERITIES = ("debug", "info", "warn", "error")


class FlightRecorder:
    """Bounded ring of structured events — what the process was doing
    right before it mattered.  Thread-safe; recording is two dict
    allocations and a deque append, cheap enough for hot-ish paths
    (retries, fault trips), and the bound (``FLAGS_flight_capacity``)
    makes a week-long run's recorder the same size as a minute-long
    one's."""

    def __init__(self, capacity: Optional[int] = None):
        self._capacity = capacity
        self._ring = None                     # lazy: flag read at first use
        # reentrant: the SIGTERM crash handler dumps the recorder from
        # a signal frame that may interrupt the main thread mid-record
        # — a plain Lock would self-deadlock exactly when the launcher
        # kills a hung child (the PTA405 rule exists because of this
        # line; the tracked rlock keeps it visible to the watchdog)
        self._lock = locks.rlock("obs.flight")
        self.dropped = 0
        # per-kind lifetime totals (NOT ring-bounded): the run ledger's
        # "flight events by kind" capture must survive ring eviction
        self._kind_totals: Dict[str, int] = {}
        # per-process monotonic event id: multi-process flight dumps
        # merge in a stable order under clock skew (within one process
        # seq order IS record order, whatever the wall clock says).
        # Monotonic for the recorder's lifetime — clear() resets the
        # ring, not the sequence, so a post-clear event still sorts
        # after everything the collector already merged
        self._seq = 0
        # incident-storm guard: per-(kind, attrs) [window_start, count]
        # — a flapping signal repeating one identical event cannot wash
        # the bounded ring of the root cause recorded before it
        self._storm: Dict[tuple, list] = {}
        self.suppressed = 0
        # event listeners (framework/incident.py subscribes): called
        # OUTSIDE the ring lock with the live ev dict — a listener may
        # stamp attrs in place (the incident-id round-trip) but must
        # never raise into record()
        self._listeners: List = []

    def _buf(self) -> "collections.deque":
        if self._ring is None:
            cap = int(flag("flight_capacity")) if self._capacity is None \
                else int(self._capacity)
            self._ring = collections.deque(maxlen=max(1, cap))
        return self._ring

    def record(self, kind: str, severity: str = "info", **attrs):
        if severity not in _SEVERITIES:
            severity = "info"
        ev = {"ts": time.time(), "severity": severity, "kind": kind,
              "attrs": attrs}
        with self._lock:
            # lifetime kind totals count EVERY event, suppressed or
            # not — the run ledger's event mix stays truthful even
            # when the storm guard keeps the ring readable
            self._kind_totals[kind] = self._kind_totals.get(kind, 0) + 1
            if self._storm_suppress_locked(kind, attrs, ev["ts"]):
                self.suppressed += 1
                monitor.stat_add("flight_suppressed_total")
                ev["suppressed"] = True
                return ev
            buf = self._buf()
            if len(buf) == buf.maxlen:
                self.dropped += 1
            self._seq += 1
            ev["seq"] = self._seq
            buf.append(ev)
        # listeners run outside the lock (a listener that records its
        # own events — incident capture does — must not re-enter it
        # holding the ring) and get the LIVE dict: attrs they stamp
        # propagate to recent()/since() readers.  A listener fault is
        # swallowed — record() never fails its caller.
        for fn in list(self._listeners):
            try:
                fn(ev)
            except Exception:      # noqa: BLE001 — listener never breaks record
                pass
        return ev

    def add_listener(self, fn):
        """Subscribe ``fn(ev)`` to every non-suppressed recorded event
        (called outside the ring lock with the live event dict — attrs
        stamped in place round-trip through recent()/since()).
        Exceptions from ``fn`` are swallowed.  Returns ``fn``."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)
        return fn

    def remove_listener(self, fn) -> bool:
        """Unsubscribe a listener; True when it was registered."""
        with self._lock:
            try:
                self._listeners.remove(fn)
                return True
            except ValueError:
                return False

    def _storm_suppress_locked(self, kind: str, attrs: dict,
                               now: float) -> bool:
        """Incident-storm dedup (lock held): once ``flight_storm_k``
        IDENTICAL ``(kind, attrs)`` events landed within
        ``flight_storm_window`` seconds, further identical ones are
        suppressed (ring skipped; ``flight_suppressed_total`` and the
        lifetime kind totals still count them) until the window rolls.
        Events differing in ANY attr (a retry's attempt number, a
        chaos trip's call count) never dedup — only a truly flapping
        signal is rate-limited."""
        try:
            window = float(flag("flight_storm_window"))
            k = int(flag("flight_storm_k"))
        except KeyError:           # flags not registered yet (early
            return False           # import order) — guard off
        if window <= 0 or k <= 0:
            return False
        try:
            key = (kind, tuple(sorted(
                (a, repr(v)) for a, v in attrs.items())))
        except Exception:          # noqa: BLE001 — unorderable attrs:
            return False           # never let the guard break record()
        st = self._storm.get(key)
        if st is None or now - st[0] > window:
            if len(self._storm) >= 512:
                # bound the guard's own memory: drop entries whose
                # window already rolled (cheap sweep, rare)
                self._storm = {kk: vv for kk, vv in self._storm.items()
                               if now - vv[0] <= window}
            self._storm[key] = [now, 1]
            return False
        st[1] += 1
        return st[1] > k

    def last_seq(self) -> int:
        """The newest event's per-process seq id (0 = nothing recorded)
        — what a telemetry pusher remembers to ship only the delta."""
        with self._lock:
            return self._seq

    def since(self, seq: int, limit: int = 256) -> List[dict]:
        """Events with ``seq`` strictly greater than the given one,
        oldest first, capped at ``limit`` (a pusher that fell far behind
        ships the newest window rather than an unbounded backlog).
        Events already evicted from the ring are simply gone — the
        lifetime ``kind_totals`` still count them."""
        with self._lock:
            buf = [ev for ev in self._buf() if ev.get("seq", 0) > seq]
        return buf[-int(limit):]

    def kind_totals(self) -> Dict[str, int]:
        """Lifetime event counts by kind (unbounded, unlike the ring) —
        what ``monitor.snapshot()`` exposes as ``flight_events`` so a
        RunRecord captures the whole run's event mix in one call."""
        with self._lock:
            return dict(self._kind_totals)

    def recent(self, n: int = 50, kind: Optional[str] = None,
               min_severity: Optional[str] = None) -> List[dict]:
        """The most recent ``n`` events, oldest first.  ``kind`` keeps
        only events of that kind; ``min_severity`` drops events below
        the floor (severity order: debug < info < warn < error) — so a
        post-mortem query like ``recent(20, min_severity="warn")``
        skips the routine chatter."""
        with self._lock:
            buf = list(self._buf())
        if kind is not None:
            buf = [ev for ev in buf if ev["kind"] == kind]
        if min_severity is not None:
            if min_severity not in _SEVERITIES:
                raise ValueError(
                    f"unknown severity {min_severity!r} — one of "
                    f"{_SEVERITIES}")
            floor = _SEVERITIES.index(min_severity)
            buf = [ev for ev in buf
                   if _SEVERITIES.index(ev["severity"]) >= floor]
        n = int(n)
        return buf[-n:] if n > 0 else []

    def clear(self):
        with self._lock:
            self._buf().clear()
            self.dropped = 0
            self._kind_totals.clear()
            self._storm.clear()
            self.suppressed = 0

    def dump(self, path: str, worker: Optional[str] = None) -> str:
        """Write the ring to ``path`` as JSON, atomically (tmp+rename
        via the fs tier's crash-safe helper) — the post-mortem artifact
        ``launch._supervise`` and the crash handler produce."""
        from paddle_tpu.distributed.fleet.utils.fs import LocalFS
        with self._lock:
            events = list(self._buf())
            dropped = self.dropped
        payload = {"worker": worker, "pid": os.getpid(),
                   "dumped_at": time.time(), "dropped": dropped,
                   "events": events}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        LocalFS().atomic_write(path, json.dumps(payload, default=str))
        return path


#: process-wide flight recorder (chaos trips, PS retries, NaN rollbacks,
#: elastic membership events all land here)
flight = FlightRecorder()


# ---------------------------------------------------------------------------
# SIGTERM emergency callbacks (the preemption grace-window contract)
# ---------------------------------------------------------------------------

#: ordered (name, fn, deadline) registry the crash handler's SIGTERM hook
#: drains BEFORE dumping the flight ring — the durable-state plane
#: registers its emergency checkpoint save here
_sigterm_callbacks: List[tuple] = []
# reentrant: the SIGTERM hook drains the registry from signal-handler
# context — a plain Lock self-deadlocks if the interrupted thread was
# inside on_sigterm/remove_sigterm_callback when the signal landed
_sigterm_lock = locks.rlock("obs.sigterm")


def on_sigterm(name: str, fn, deadline: Optional[float] = None):
    """Register a deadline-bounded emergency callback for SIGTERM.

    When the :func:`install_crash_handler` SIGTERM hook fires, every
    registered callback runs (registration order) on a helper thread
    joined with its deadline (``FLAGS_ckpt_emergency_deadline`` when
    None) — a hung save cannot eat the platform's grace window; the
    flight dump and the chained/re-delivered signal still happen.  Each
    run is recorded (``sigterm.callback`` flight event: ok / error /
    timeout).  Re-registering a name replaces the previous callback
    (the training loop re-arms each generation with fresh state)."""
    with _sigterm_lock:
        _sigterm_callbacks[:] = [c for c in _sigterm_callbacks
                                 if c[0] != name]
        _sigterm_callbacks.append((name, fn, deadline))
    return fn


def remove_sigterm_callback(name: str) -> bool:
    """Drop a registered emergency callback; True when it existed."""
    with _sigterm_lock:
        n = len(_sigterm_callbacks)
        _sigterm_callbacks[:] = [c for c in _sigterm_callbacks
                                 if c[0] != name]
        return len(_sigterm_callbacks) < n


def _run_sigterm_callbacks():
    with _sigterm_lock:
        cbs = list(_sigterm_callbacks)
    for name, fn, deadline in cbs:
        if deadline is None:
            deadline = float(flag("ckpt_emergency_deadline"))
        box: Dict[str, Any] = {}

        def run(fn=fn, box=box):
            try:
                fn()
                box["status"] = "ok"
            except BaseException as e:  # noqa: BLE001 — post-mortem record
                box["status"] = "error"
                box["error"] = repr(e)

        t = threading.Thread(target=run, name=f"sigterm-{name}",
                             daemon=True)
        t0 = time.monotonic()
        t.start()
        t.join(deadline)
        status = box.get("status", "timeout")
        flight.record("sigterm.callback",
                      severity="info" if status == "ok" else "error",
                      name=name, status=status,
                      elapsed_s=round(time.monotonic() - t0, 3),
                      **({"error": box["error"]} if "error" in box else {}))
        monitor.stat_add(f"sigterm_callback_{status}_total")


def install_crash_handler(worker: Optional[str] = None,
                          flight_dir: Optional[str] = None,
                          chain: bool = True, sigterm: bool = True):
    """Hook ``sys.excepthook`` so an uncaught exception dumps the flight
    recorder to ``<flight_dir>/flight_<worker>.json`` before the normal
    traceback.  ``worker`` defaults to the elastic worker id the
    launcher exported (``PADDLE_ELASTIC_WORKER_ID``) or ``pid<n>``;
    ``flight_dir`` to ``FLAGS_flight_dir`` (cwd when empty).  Returns
    the installed hook (tests call it directly; ``chain=False``
    suppresses the chained traceback print).

    ``sigterm=True`` (default) additionally dumps on SIGTERM: a hung
    child the launcher/agent kills never reaches the excepthook, and a
    post-mortem with no flight file is exactly when one is needed.  The
    SIGTERM dump chains to the previously installed handler — or, under
    the default disposition, restores it and re-delivers the signal so
    the exit status still says SIGTERM.  Installing from a non-main
    thread skips the signal hook (the excepthook still installs)."""
    import sys
    worker_id = worker or os.environ.get("PADDLE_ELASTIC_WORKER_ID") \
        or f"pid{os.getpid()}"
    base = flight_dir if flight_dir is not None else \
        (str(flag("flight_dir")) or ".")
    prev = sys.excepthook

    def _dump(kind: str, **attrs):
        flight.record(kind, severity="error", worker=worker_id, **attrs)
        try:
            flight.dump(os.path.join(base, f"flight_{worker_id}.json"),
                        worker=worker_id)
        except OSError:
            pass                    # a full disk must not mask the crash

    def hook(exc_type, exc, tb):
        _dump("crash", exc=repr(exc))
        if chain:
            prev(exc_type, exc, tb)

    sys.excepthook = hook
    if sigterm:
        import signal as _signal
        prev_term = _signal.getsignal(_signal.SIGTERM)

        def term_hook(signum, frame):
            # emergency callbacks (deadline-bounded) run FIRST: the
            # whole point of the grace window is the state they save
            _run_sigterm_callbacks()
            _dump("sigterm")
            if callable(prev_term):
                prev_term(signum, frame)
            elif prev_term is _signal.SIG_IGN:
                # explicitly ignored before we installed: the dump must
                # not turn an ignored SIGTERM into process death
                return
            else:
                # default disposition (or a handler we cannot chain):
                # restore and re-deliver, so the process still dies
                # with the SIGTERM exit status the supervisor expects
                _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
                os.kill(os.getpid(), _signal.SIGTERM)

        try:
            _signal.signal(_signal.SIGTERM, term_hook)
        except ValueError:
            pass                    # non-main thread: no signal hook
    return hook


# ---------------------------------------------------------------------------
# metrics export plane
# ---------------------------------------------------------------------------

class MetricsReporter:
    """Background thread rendering ``monitor.export_prometheus()`` to
    ``path`` every ``interval`` seconds (``FLAGS_metrics_export_interval``
    default), atomically via tmp+rename — a scraper or node exporter
    textfile collector never sees a torn file.  ``write_once()`` is the
    synchronous form (tests, final flush).

    **Push mode** (``collector=``): additionally (or, with
    ``path=None``, exclusively) ship each interval's telemetry to the
    central cluster collector (``framework/collector.py``) —
    ``monitor.snapshot()`` deltas, span summaries, and flight-event
    deltas, stamped with a per-process monotonic push seq.  Pushes are
    fire-and-forget through a bounded queue with a drop counter and the
    ``collector.rpc`` chaos point: a slow, dead, or fault-injected
    collector can never slow or crash the process being observed.
    ``collector`` is a ``host:port`` string or a prebuilt
    ``collector.CollectorClient``; ``role``/``worker`` label the pushed
    payloads (defaulting to the launcher's ``PADDLE_ROLE`` /
    ``PADDLE_TRACE_LABEL`` env)."""

    def __init__(self, path: Optional[str], interval: Optional[float] = None,
                 collector=None, worker: Optional[str] = None,
                 role: Optional[str] = None, payload_extra=None):
        if path is None and collector is None:
            raise ValueError("MetricsReporter needs a path, a collector "
                             "endpoint, or both")
        self.path = path
        self.interval = float(flag("metrics_export_interval")) \
            if interval is None else float(interval)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.writes = 0
        self.pushes = 0
        self._collector = None
        self._payload_extra = payload_extra
        if collector is not None:
            from paddle_tpu.framework import collector as _collector_mod
            if isinstance(collector, str):
                self._collector = _collector_mod.CollectorClient(
                    collector, worker=worker, role=role)
            else:
                self._collector = collector

    @property
    def collector(self):
        """The push-mode CollectorClient (None in file-only mode)."""
        return self._collector

    def write_once(self) -> str:
        text = ""
        if self.path is not None:
            # render only when there is a file to write: a push-only
            # reporter ships monitor.snapshot()-based payloads, and
            # serializing the whole exposition text to discard it
            # would tax every pushing process each interval
            text = monitor.export_prometheus()
            from paddle_tpu.distributed.fleet.utils.fs import LocalFS
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            LocalFS().atomic_write(self.path, text)
            self.writes += 1  # pta: disable=PTA403 (happens-before sequencing: start()'s initial write precedes the thread, stop()'s final write follows the join — never concurrent with _loop)
        if self._collector is not None:
            from paddle_tpu.framework import collector as _collector_mod
            extra = None
            if self._payload_extra is not None:
                try:
                    extra = self._payload_extra()
                except Exception:  # noqa: BLE001 — telemetry never crashes
                    extra = None
            self._collector.push(_collector_mod.local_payload(
                since_seq=self._collector.flight_seq_sent, extra=extra))
            self.pushes += 1  # pta: disable=PTA403 (same happens-before sequencing as self.writes above)
        return text

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.write_once()
            except OSError:
                pass                # transient fs trouble: keep reporting

    def start(self) -> "MetricsReporter":
        self.write_once()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            # daemon is deliberate: the reporter must never block
            # interpreter exit, and the export IS tmp+rename — a
            # mid-write kill leaves a whole old file (at worst plus a
            # dead .tmp)
            name="metrics-reporter")  # pta: disable=PTA407 (tmp+rename export is kill-safe; owner: observability)
        self._thread.start()
        return self

    def stop(self, final_write: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if final_write:
            try:
                self.write_once()
            except OSError:
                pass
        if self._collector is not None:
            self._collector.stop()


# ---------------------------------------------------------------------------
# prometheus text-format grammar check (the CI lane's gate)
# ---------------------------------------------------------------------------

import re as _re  # noqa: E402

_PROM_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_PROM_COMMENT_RE = _re.compile(
    rf"^# (HELP {_PROM_NAME} .*|TYPE {_PROM_NAME} "
    r"(counter|gauge|histogram|summary|untyped))$")
_PROM_SAMPLE_RE = _re.compile(
    rf"^({_PROM_NAME})"
    r"(?:\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\""
    r"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\")*\})?"
    r" [-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN)"
    r"(?: [0-9]+)?$")
_PROM_LE_RE = _re.compile(r'le="([^"]+)"')


def validate_prometheus(text: str, require_help: bool = False) -> int:
    """Validate ``text`` against the Prometheus exposition text-format
    grammar (comment/sample line shapes) plus histogram invariants:
    cumulative non-decreasing buckets, a ``+Inf`` bucket equal to
    ``_count``, and ``_sum``/``_count`` present for every histogram.
    A ``# HELP`` may appear at most once per metric and must precede
    that metric's samples; ``require_help=True`` additionally demands a
    HELP line for every ``# TYPE``-declared metric — the full contract
    a real Prometheus scraper expects of ``export_prometheus()``
    output.  Returns the number of sample lines; raises ``ValueError``
    on the first violation."""
    samples = 0
    hist_names: List[str] = []
    type_names: List[str] = []
    help_names: set = set()
    sampled_names: set = set()
    values: Dict[str, float] = {}
    buckets: Dict[str, List[tuple]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("#"):
            if not _PROM_COMMENT_RE.match(line):
                raise ValueError(f"line {i}: malformed comment: {line!r}")
            if line.startswith("# TYPE "):
                type_names.append(line.split()[2])
                if line.endswith(" histogram"):
                    hist_names.append(line.split()[2])
            elif line.startswith("# HELP "):
                h = line.split()[2]
                if h in help_names:
                    raise ValueError(f"line {i}: duplicate HELP for {h}")
                if h in sampled_names:
                    raise ValueError(
                        f"line {i}: HELP for {h} after its samples")
                help_names.add(h)
            continue
        m = _PROM_SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {i}: malformed sample: {line!r}")
        samples += 1
        name = m.group(1)
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = name[:-len(suffix)]
                break
        sampled_names.add(name)
        sampled_names.add(base)
        rest = line.split("} ", 1)[1] if "} " in line \
            else line.split(" ", 1)[1]
        val = float(rest.split(" ")[0])
        if name.endswith("_bucket"):
            le = _PROM_LE_RE.search(line)
            if le is None:
                raise ValueError(f"line {i}: bucket without le label")
            buckets.setdefault(name[:-len("_bucket")], []).append(
                (le.group(1), val))
        else:
            values[name] = val
    for h in hist_names:
        bks = buckets.get(h)
        if not bks:
            raise ValueError(f"histogram {h}: no buckets")
        nums = [float("inf") if le == "+Inf" else float(le)
                for le, _ in bks]
        counts = [c for _, c in bks]
        if nums != sorted(nums) or nums[-1] != float("inf"):
            raise ValueError(f"histogram {h}: buckets not ascending "
                             "or missing +Inf")
        if any(b > a for b, a in zip(counts, counts[1:])):
            raise ValueError(f"histogram {h}: buckets not cumulative")
        if h + "_count" not in values or h + "_sum" not in values:
            raise ValueError(f"histogram {h}: missing _sum/_count")
        if counts[-1] != values[h + "_count"]:
            raise ValueError(f"histogram {h}: +Inf bucket "
                             f"{counts[-1]} != _count {values[h + '_count']}")
    if require_help:
        missing = [n for n in type_names if n not in help_names]
        if missing:
            raise ValueError(
                f"metrics declared without a # HELP line: {missing[:5]}"
                + ("..." if len(missing) > 5 else ""))
    return samples
