"""Step-granularity NaN rollback — the missing tier between detection
and epoch recovery.

The reference gives two failure tools at opposite granularities: the
per-op NaN/Inf watcher (FLAGS_check_nan_inf, framework/details/
nan_inf_utils.h) *detects* a blow-up, and auto-checkpoint
(TrainEpochRange) *recovers* — but only at epoch boundaries, losing
everything since the last save.  :class:`ResilientTrainStep` closes the
gap: snapshot last-good training state on host every K steps, detect a
non-finite loss (or non-finite params) after each step, and
skip-and-restore instead of letting one bad batch corrupt the run —
raising only after M consecutive bad steps, when the blow-up is clearly
systematic rather than transient.

Works over any step with the TrainStep surface (``model``, ``optimizer``,
``_opt_states``, callable returning a loss Tensor): jit.TrainStep,
ShardedTrainStep, PSTrainStep's dense tier.  Snapshots are host numpy
copies, so donated device buffers are never pinned and restore survives
``donate=True`` (where the pre-step device arrays are already consumed).

AMP: with a fp16 :class:`~paddle_tpu.amp.GradScaler` passed as
``scaler``, every detected bad step feeds the scaler's dynamic-scaling
state machine (found_inf → update()), so injected NaN storms also drive
the loss scale down exactly as update_loss_scaling_op would.

The ``train.step_grads`` chaos point runs over the step inputs before
dispatch: arming it with ``mode="nan"`` NaN-poisons the batch, the real
forward/backward propagates the poison into loss and grads, and the
rollback path is exercised end-to-end (tests/test_chaos.py proves a
poisoned run still reaches the un-poisoned final loss).

Model numerics (FLAGS_numerics): when the wrapped step computes the
in-jit numerics aux (framework/numerics.py), the finite check reads
that record instead of running a host ``np.isfinite`` sweep — loss,
every gradient leaf, and (``check_state``) every post-update parameter
leaf in one fetch — and a skipped step's ``train.nan_skip`` flight
event names the first offending leaf (``first_bad_leaf``), the step-
granularity analogue of the reference watcher naming the offending op.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework import chaos, incident, monitor
from paddle_tpu.framework.observability import flight

__all__ = ["ResilientTrainStep"]


class ResilientTrainStep:
    """Rollback wrapper: snapshot every ``snapshot_every`` good steps,
    restore-and-skip on a non-finite step, raise FloatingPointError after
    ``max_consecutive_bad`` consecutive bad steps.

    A rollback restores the most recent snapshot — with
    ``snapshot_every=K`` up to K-1 good steps are re-lost; K=1 (default)
    makes rollback exact at the cost of one host copy of
    params+opt-state per step.  Raise K when step time is small relative
    to state size.

    ``check_state=True`` additionally sweeps the post-step parameters for
    non-finite values, catching the finite-loss/NaN-grad case the loss
    check alone misses (the grad-norm watch of the reference's
    check_nan_inf at step granularity).

    Return value: the step's loss Tensor.  On a skipped step it is the
    detected NON-FINITE loss (a NaN scalar when the wrapped step raised
    before returning one) — always float()-able, never None — and
    ``last_step_skipped`` is True; filter on that flag before folding
    losses into running statistics."""

    def __init__(self, step, snapshot_every: int = 1,
                 max_consecutive_bad: int = 3, scaler=None,
                 check_state: bool = False):
        if snapshot_every < 1:
            raise ValueError("snapshot_every must be >= 1")
        if max_consecutive_bad < 1:
            raise ValueError("max_consecutive_bad must be >= 1")
        self.step = step
        self.snapshot_every = snapshot_every
        self.max_consecutive_bad = max_consecutive_bad
        self.scaler = scaler
        self.check_state = check_state
        self._snap: Optional[dict] = None
        self._good_since_snap = 0
        self.consecutive_bad = 0
        self.skipped_steps = 0
        self.rollbacks = 0
        self.last_step_skipped = False
        # NaN provenance (FLAGS_numerics armed on the wrapped step):
        # the first parameter leaf with a non-finite grad/param on the
        # most recently skipped step — also stamped into the
        # train.nan_skip flight event as first_bad_leaf
        self.last_bad_leaf: Optional[str] = None
        self.membership_epoch: Optional[int] = None
        self.membership_events = 0
        # optional durable tier (attach_durable): periodic verified
        # generations + the SIGTERM emergency save
        self._durable = None
        self._durable_every = 0
        self._durable_mode = "async"
        self._durable_ws: Optional[int] = None

    # -- durable tier --------------------------------------------------------
    def attach_durable(self, manager, every: int = 0, mode: str = "async",
                       world_size: Optional[int] = None,
                       arm_preemption: bool = True):
        """Wire the rollback tier to a multi-generation durable store
        (:class:`paddle_tpu.distributed.durable.CheckpointManager`).

        ``every=N`` persists a verified, committed generation after
        every N-th GOOD step (``mode="async"`` by default: the host
        snapshot happens at the step boundary, the write off-thread —
        the rollback snapshot this class already takes makes the extra
        host copy cheap by comparison); 0 leaves cadence to the caller.
        Only good steps count: a rolled-back step must never become a
        generation.  ``arm_preemption`` registers the SIGTERM emergency
        save (deadline-bounded, through the install_crash_handler
        chain), so a preempted worker lands one final generation of its
        last-good state inside the agent's ``term_grace`` window."""
        self._durable = manager
        self._durable_every = int(every)
        self._durable_mode = mode
        self._durable_ws = world_size
        if arm_preemption:
            manager.arm_emergency_save(
                self.step,
                lambda: int(getattr(self.step.optimizer,
                                    "_global_step", 0)))
        return manager

    def _maybe_save_durable(self):
        if self._durable is None or self._durable_every <= 0:
            return
        gen = int(getattr(self.step.optimizer, "_global_step", 0))
        if gen > 0 and gen % self._durable_every == 0:
            self._durable.save(self.step, gen, world_size=self._durable_ws,
                               mode=self._durable_mode)

    # -- snapshot / restore --------------------------------------------------
    def snapshot(self):
        """Host-copy the wrapped step's full training state (params,
        buffers, optimizer slots, global step)."""
        model, opt = self.step.model, self.step.optimizer
        self._snap = {
            "params": {n: np.asarray(p._data)
                       for n, p in model.named_parameters()},
            "buffers": {n: np.asarray(b._data)
                        for n, b in model.named_buffers() if b is not None},
            "opt_states": jax.tree_util.tree_map(
                np.asarray, self.step._opt_states)
            if self.step._opt_states is not None else None,
            "global_step": int(getattr(opt, "_global_step", 0)),
        }
        self._good_since_snap = 0

    def restore(self):
        """Reinstall the last snapshot into the live model/optimizer."""
        if self._snap is None:
            raise RuntimeError("no snapshot to restore")
        model, opt = self.step.model, self.step.optimizer
        snap = self._snap
        for n, p in model.named_parameters():
            p._data = jnp.asarray(snap["params"][n])
        for n, b in model.named_buffers():
            if b is not None and n in snap["buffers"]:
                b._data = jnp.asarray(snap["buffers"][n])
        if snap["opt_states"] is not None:
            self.step._opt_states = jax.tree_util.tree_map(
                jnp.asarray, snap["opt_states"])
        if hasattr(opt, "_global_step"):
            opt._global_step = snap["global_step"]
        self._good_since_snap = 0
        monitor.stat_add("train_restores_total")
        flight.record("train.restore", severity="warn",
                      restored_step=snap["global_step"],
                      rollbacks=self.rollbacks)

    def membership_changed(self, epoch: Optional[int] = None):
        """Surface a membership-epoch bump (elastic shrink/grow) to the
        rollback tier: snapshot the CURRENT last-good state immediately,
        *before* the re-form path refreshes roles and re-shards layouts —
        so whatever the re-form restores or the next rollback needs is
        never newer than the membership it was computed under.  Called by
        :func:`paddle_tpu.distributed.elastic.reform`."""
        self.membership_epoch = epoch
        self.membership_events += 1
        self.snapshot()

    # -- detection -----------------------------------------------------------
    def _finite(self, loss, numerics_rec=None) -> bool:
        """The per-step finite verdict.  With a fresh model-numerics
        record (FLAGS_numerics armed on the wrapped step) the verdict
        comes from the in-jit aux — loss, every gradient leaf, and
        (``check_state``) every post-update parameter leaf in ONE
        reduction that rode back with the step outputs, replacing both
        the host ``np.isfinite`` sweep and the per-leaf device reduces
        of the legacy path.  Disarmed, the host path below is the
        fallback and behaves exactly as before."""
        if numerics_rec is not None:
            return numerics_rec.finite(check_params=self.check_state)
        arr = loss._data if hasattr(loss, "_data") else loss
        if not bool(np.all(np.isfinite(np.asarray(arr)))):
            return False
        if self.check_state:
            for _, p in self.step.model.named_parameters():
                d = p._data
                if jnp.issubdtype(d.dtype, jnp.floating) and \
                        not bool(jnp.all(jnp.isfinite(d))):
                    return False
        return True

    # -- step ----------------------------------------------------------------
    def __call__(self, *inputs):
        if self._snap is None:
            self.snapshot()
        # postmortem ring: PRE-poison inputs + rng + pre-step state, so
        # a replay re-arms the recorded chaos schedule and re-derives
        # the poison itself (host-only reads; one flag lookup disarmed)
        incident.maybe_note(self, inputs)
        inputs = chaos.fault_point("train.step_grads", payload=inputs)  # pta: disable=PTA301 (ResilientTrainStep IS the recovery wrapper)
        self.last_step_skipped = False
        # a FRESH numerics record (stashed by the wrapped step during
        # THIS call, when FLAGS_numerics is armed) carries the in-jit
        # finite verdict + per-leaf NaN provenance; a stale one from an
        # earlier step must not be trusted — compare identity around
        # the call
        rec_before = getattr(self.step, "last_numerics", None)
        rec = None
        try:
            loss = self.step(*inputs)
            rec = getattr(self.step, "last_numerics", None)
            rec = rec if rec is not rec_before else None
            finite = self._finite(loss, rec)
        except FloatingPointError:
            # FLAGS_check_nan_inf armed inside the wrapped step: same
            # recovery path as our own detection.  Stand in a NaN scalar
            # for the loss the step never returned, so the skipped-step
            # return is always float()-able (see the docstring note).
            from paddle_tpu.core import Tensor
            rec = getattr(self.step, "last_numerics", None)
            rec = rec if rec is not rec_before else None
            loss = Tensor(jnp.asarray(jnp.nan, dtype=jnp.float32))
            finite = False
        if self.scaler is not None:
            self.scaler._found_inf = not finite
            self.scaler.update()
        if finite:
            self.consecutive_bad = 0
            self._good_since_snap += 1
            if self._good_since_snap >= self.snapshot_every:
                self.snapshot()
            self._maybe_save_durable()
            return loss
        self.consecutive_bad += 1
        self.skipped_steps += 1
        self.rollbacks += 1
        self.last_step_skipped = True
        self.last_bad_leaf = rec.first_bad_leaf() if rec is not None \
            else None
        monitor.stat_add("train_nan_skips_total")
        flight.record("train.nan_skip", severity="warn",
                      consecutive=self.consecutive_bad,
                      skipped_total=self.skipped_steps,
                      first_bad_leaf=self.last_bad_leaf)
        self.restore()
        if self.consecutive_bad >= self.max_consecutive_bad:
            flight.record("train.abort", severity="error",
                          consecutive=self.consecutive_bad)
            raise FloatingPointError(
                f"ResilientTrainStep: {self.consecutive_bad} consecutive "
                "non-finite steps — rollback cannot outrun a systematic "
                "blow-up (check lr / data / loss scale)")
        return loss

    def flush(self):
        if hasattr(self.step, "flush"):
            self.step.flush()
