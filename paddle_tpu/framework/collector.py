"""Central cluster telemetry collector — the fleet-level view.

Every observability plane before this one is per-process: each worker
writes its own span file, keeps its own monitor registry, and runs its
own detectors, so a straggling worker, a skewed PS shard, or a hot
embedding row is invisible until someone hand-merges files after the
run.  This module is the missing aggregation point, three parts on the
design center the whole stack shares (telemetry must never slow or
crash the thing it observes):

* :class:`CollectorClient` — the **fire-and-forget push path** every
  process uses.  ``push(payload)`` enqueues onto a bounded queue
  (``FLAGS_collector_queue_capacity``); a background sender ships each
  payload over the PS RPC wire framing (length-prefixed JSON header —
  byte-compatible with ``ps/service.py``'s ``_send_msg``/``_recv_msg``,
  re-implemented header-only here so the collector stays off the
  PS/device-table import chain) with the ``collector.rpc`` chaos point
  at its head.  A full
  queue, a dead collector, a timeout, or an injected fault is a DROP,
  counted into ``collector_dropped_total`` — the pushing train loop is
  bit-identical to a collector-less run (pinned by the CI gate).
  Pushes carry a per-process monotonic ``seq`` so the collector can see
  its own losses (gaps) without any acknowledgement protocol.

* :class:`CollectorServer` — the **aggregation + cross-worker
  detection** service.  ``report`` ops fold each process's
  ``monitor.snapshot()`` deltas, span summaries, flight-event deltas
  (merged in per-process-seq order — stable under clock skew), and PS
  table telemetry (per-shard request counts + the bounded
  :class:`~paddle_tpu.distributed.ps.device_table.HotRowSketch` top-k)
  into one cluster state.  The existing ``health.Detector`` runs
  *across* workers: each trainer's per-interval step-time mean feeds a
  per-worker detector, and a **straggler score** (interval mean over
  the leave-one-out median of its peers) names the slow rank —
  surfaced in the live view, reported to
  ``ElasticAgent.note_stragglers`` via ``on_straggler``, and stamped
  into a cluster-level run-ledger record
  (:meth:`CollectorServer.capture_record`) that ``perf_report
  compare`` gates cross-run.

* ``tools/cluster_top.py`` — the **live text view** rendered from the
  collector's ``view`` op (or, collector-less, by scraping PS ``stat``
  ops): per-worker step p50/p99, stall %, RPC latency, anomaly/flight
  counts, straggler flags, hot tables.

Wiring: ``launch`` exports ``PADDLE_COLLECTOR_ENDPOINT`` (and
``PADDLE_ROLE``) to every child — server AND trainer roles — when
``--collector`` (in-launcher collector) or ``--collector_endpoint`` is
given; :func:`auto_reporter` turns that env (or
``FLAGS_collector_endpoint``) into a started push-mode
``MetricsReporter`` in one call.
"""
from __future__ import annotations

import json
import os
import queue
import socket
import socketserver
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from paddle_tpu.framework import chaos, locks, monitor
from paddle_tpu.framework.flags import flag
from paddle_tpu.framework.observability import flight, tracer

__all__ = ["CollectorClient", "CollectorServer",
           "aggregate_table_shards", "auto_reporter",
           "collector_endpoint", "local_payload", "merge_flight_events",
           "request", "serve"]

VIEW_SCHEMA_VERSION = 1


# ---------------------------------------------------------------------------
# wire framing — byte-compatible with the PS RPC protocol
# (ps/service.py _send_msg/_recv_msg), restricted to header-only
# messages: telemetry is pure JSON, and re-implementing the 40 lines
# here keeps the collector off the PS/accelerator import chain — a
# launcher-hosted collector never touches device tables or numpy
# buffer plumbing, and no device ever gets initialized on its account
# ---------------------------------------------------------------------------

def _recvall(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _send(sock: socket.socket, header: dict) -> int:
    meta = dict(header)
    meta["__bufs__"] = []
    hb = json.dumps(meta, default=str).encode()
    msg = struct.pack("<I", len(hb)) + hb
    sock.sendall(msg)
    return len(msg)


def _recv(sock: socket.socket) -> dict:
    (hlen,) = struct.unpack("<I", _recvall(sock, 4))
    header = json.loads(_recvall(sock, hlen))
    for _spec in header.pop("__bufs__", []) or []:
        # drain any buffers a PS-framing peer attached; telemetry
        # itself never carries them
        (blen,) = struct.unpack("<Q", _recvall(sock, 8))
        _recvall(sock, blen)
    return header


def request(endpoint: str, header: dict,
            timeout: Optional[float] = None) -> dict:
    """One-shot RPC over the PS framing: dial ``endpoint``, send
    ``header``, return the reply header.  What ``cluster_top`` uses for
    both the collector's ``view`` op and the PS ``stat`` fallback
    scrape (same wire format on both services)."""
    host, port = endpoint.rsplit(":", 1)
    t = float(flag("collector_timeout")) if timeout is None else timeout
    with socket.create_connection((host, int(port)), timeout=t) as s:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send(s, header)
        return _recv(s)


def collector_endpoint() -> Optional[str]:
    """The collector endpoint this process should push to:
    ``PADDLE_COLLECTOR_ENDPOINT`` (the launcher's per-child env) wins
    over ``FLAGS_collector_endpoint``; None when neither is set."""
    ep = os.environ.get("PADDLE_COLLECTOR_ENDPOINT") \
        or str(flag("collector_endpoint") or "")
    return ep or None


# ---------------------------------------------------------------------------
# payload assembly (the pushing side)
# ---------------------------------------------------------------------------

_HIST_KEYS = ("count", "sum", "mean", "p50", "p95", "p99", "max")

# incremental span-file cursor: the push path must not re-read (and
# re-aggregate) the whole ever-growing span file every interval — that
# is the O(n²)-cumulative-I/O shape the run ledger explicitly rejected.
# Per span file we remember the byte offset already folded in and keep
# cumulative per-name aggregates (count/total/max/errors exact; p99
# over a bounded window of recent durations)
_SPAN_WINDOW = 512
_span_cursors: Dict[str, dict] = {}
_span_lock = locks.lock("collector.spans")


def _own_span_rows(path: str) -> List[dict]:
    with _span_lock:
        cur = _span_cursors.get(path)
        if cur is None:
            cur = _span_cursors[path] = {"offset": 0, "names": {},
                                         "ino": None}
        try:
            with open(path, "rb") as f:
                st = os.fstat(f.fileno())
                if st.st_ino != cur.get("ino") or \
                        st.st_size < cur["offset"]:
                    # segment rotated (FLAGS_trace_max_mb) or truncated:
                    # this is a FRESH file — restart the byte cursor at
                    # 0 (everything in it is new, so no double count;
                    # spans of the rotated-away segment that were never
                    # read are simply gone — the tracer counts them in
                    # trace_spans_dropped_total).  The per-name
                    # aggregates keep accumulating across segments
                    cur["offset"] = 0
                    cur["ino"] = st.st_ino
                f.seek(cur["offset"])
                chunk = f.read()
        except OSError:
            return []
        if chunk:
            # fold only COMPLETE lines; a torn tail stays unconsumed
            # until its newline lands
            cut = chunk.rfind(b"\n") + 1
            cur["offset"] += cut
            for line in chunk[:cut].splitlines():
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") != "span":
                    continue
                name = str(rec.get("name", "?"))
                ms = float(rec.get("dur", 0.0)) / 1e3
                agg = cur["names"].get(name)
                if agg is None:
                    agg = cur["names"][name] = {
                        "count": 0, "total_ms": 0.0, "max_ms": 0.0,
                        "errors": 0, "recent": deque(maxlen=_SPAN_WINDOW)}
                agg["count"] += 1
                agg["total_ms"] += ms
                agg["max_ms"] = max(agg["max_ms"], ms)
                agg["errors"] += int(rec.get("status") == "error")
                agg["recent"].append(ms)
        rows = []
        for name, agg in cur["names"].items():
            recent = sorted(agg["recent"])
            p99 = recent[min(len(recent) - 1,
                             max(0, int(0.99 * len(recent) + 0.5) - 1))] \
                if recent else 0.0
            rows.append({"name": name, "count": agg["count"],
                         "total_ms": round(agg["total_ms"], 3),
                         "mean_ms": round(agg["total_ms"] / agg["count"],
                                          3) if agg["count"] else 0.0,
                         "p99_ms": round(p99, 3),
                         "max_ms": round(agg["max_ms"], 3),
                         "errors": agg["errors"]})
        rows.sort(key=lambda r: r["total_ms"], reverse=True)
        return rows


def local_payload(since_seq: int = 0, extra: Optional[dict] = None,
                  labels=None) -> dict:
    """One telemetry payload for this process: the full
    ``monitor.snapshot()`` stats + histogram summaries (the collector
    diffs consecutive payloads itself, so the pusher stays stateless),
    the flight-event DELTA since ``since_seq`` (each event stamped with
    its per-process monotonic seq), and — when tracing is armed — this
    process's own span-summary rows (folded incrementally: each push
    reads only the span file's new bytes; p99 is over the last
    ``_SPAN_WINDOW`` spans per name, count/total/max/errors exact).
    ``extra`` merges producer-specific sections in (e.g. the PS
    server's per-table telemetry)."""
    snap = monitor.snapshot(labels=labels)
    hists = {name: {k: rec.get(k) for k in _HIST_KEYS}
             for name, rec in snap.get("histograms", {}).items()}
    payload: Dict[str, Any] = {
        "stats": snap.get("stats", {}),
        "hists": hists,
        "flight_events": snap.get("flight_events", {}),
        "flight": flight.since(since_seq),
        "flight_last_seq": flight.last_seq(),
    }
    if tracer.enabled:
        try:
            rows = _own_span_rows(tracer.path())
            if rows:
                payload["spans"] = rows
        except Exception:  # noqa: BLE001 — telemetry never crashes
            pass
    try:
        # incident notices (postmortem plane): the CUMULATIVE bounded
        # queue ships whole each push — fire-and-forget pushes drop, so
        # the server dedups by id rather than the client draining
        from paddle_tpu.framework import incident as _incident
        notices = _incident.drain_notices()
        if notices:
            payload["incidents"] = notices
    except Exception:  # noqa: BLE001 — telemetry never crashes
        pass
    if extra:
        payload.update(extra)
    return payload


def merge_flight_events(events_by_worker: Dict[Any, List[dict]]
                        ) -> List[dict]:
    """Merge per-process flight events into one stable order.  Within a
    process (= one group key), order follows the per-process monotonic
    ``seq`` (record order, whatever the wall clock did); across
    processes, events interleave by a MONOTONICIZED timestamp — each
    event's effective ts is the max of its own and every earlier
    same-process event's — so clock skew or a backwards wall clock can
    never reorder one process's events, and ties break
    deterministically on (group, seq).  Group keys need only sort
    consistently: plain worker names for dump-file merges, ``(worker,
    incarnation)`` pairs in the collector (a restarted worker's seq
    rewinds, so its incarnations are distinct seq streams and must not
    interleave by seq).  Each merged event carries its ``worker``
    (pre-stamped events keep theirs)."""
    keyed = []
    for key in sorted(events_by_worker, key=str):
        eff = float("-inf")
        worker = key[0] if isinstance(key, tuple) else key
        for ev in sorted(events_by_worker[key],
                         key=lambda e: e.get("seq", 0)):
            eff = max(eff, float(ev.get("ts", 0.0)))
            out = dict(ev)
            out.setdefault("worker", worker)
            keyed.append((eff, str(key), ev.get("seq", 0), out))
    keyed.sort(key=lambda t: (t[0], t[1], t[2]))
    return [ev for _, _, _, ev in keyed]


def aggregate_table_shards(by_shard: Dict[str, dict]) -> dict:
    """Fold per-shard table telemetry (each shard's latest cumulative
    ``{pulls, pushes, rows_pulled, hot_rows}``) into one table row:
    request totals, shard skew (max pulls over the per-shard mean), and
    the cluster-wide hot-row top-k — per-shard rows are disjoint by
    ``id % n`` routing, so summing per-shard counts never double
    counts.  ONE definition shared by the collector's ``view`` and
    ``cluster_top``'s collector-less PS-scrape fallback, so the two
    views cannot silently diverge."""
    shards = {w: {"pulls": int(t.get("pulls") or 0),
                  "pushes": int(t.get("pushes") or 0),
                  "rows_pulled": int(t.get("rows_pulled") or 0)}
              for w, t in by_shard.items()}
    pulls = [v["pulls"] for v in shards.values()]
    total = sum(pulls)
    skew = (max(pulls) / (total / len(pulls))) if total and pulls else 1.0
    hot: Dict[int, int] = {}
    for t in by_shard.values():
        for rid, cnt in (t.get("hot_rows") or []):
            hot[int(rid)] = hot.get(int(rid), 0) + int(cnt)
    hot_rows = sorted(hot.items(), key=lambda kv: (-kv[1], kv[0]))[:32]
    return {"pulls": total,
            "pushes": sum(v["pushes"] for v in shards.values()),
            "by_shard": shards,
            "shard_skew": round(skew, 4),
            "hot_rows": hot_rows}


# ---------------------------------------------------------------------------
# client: bounded-queue fire-and-forget pusher
# ---------------------------------------------------------------------------

class CollectorClient:
    """Fire-and-forget telemetry pusher.  ``push`` never blocks and
    never raises: a payload enqueued while the queue is full — or whose
    send hits a dead collector, a timeout, or an injected
    ``collector.rpc`` fault — is dropped and counted
    (``collector_dropped_total``).  The background sender keeps one
    persistent connection, redialing lazily after a failure; there are
    no retries (the next interval's push IS the retry, and a retry
    storm against a dead collector is exactly the interference this
    design exists to rule out)."""

    def __init__(self, endpoint: str, worker: Optional[str] = None,
                 role: Optional[str] = None,
                 capacity: Optional[int] = None,
                 timeout: Optional[float] = None):
        self.endpoint = str(endpoint)
        self.worker = worker or os.environ.get("PADDLE_TRACE_LABEL") \
            or os.environ.get("PADDLE_ELASTIC_WORKER_ID") \
            or f"pid{os.getpid()}"
        self.role = role or os.environ.get("PADDLE_ROLE") \
            or {"PSERVER": "server", "TRAINER": "trainer"}.get(
                os.environ.get("TRAINING_ROLE", ""), "worker")
        cap = int(flag("collector_queue_capacity")) if capacity is None \
            else int(capacity)
        self.timeout = float(flag("collector_timeout")) if timeout is None \
            else float(timeout)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, cap))
        self._stop = threading.Event()
        self._seq = 0
        # guards the push seq AND the drop counter: _drop runs on both
        # the caller thread (queue full) and the sender thread (send
        # failure), and an unlocked += loses counts (PTA403)
        self._seq_lock = locks.lock("collector.client.seq")
        # per-INCARNATION identity (the PsClient._push_ident idiom): an
        # elastic-restarted worker reuses its name but restarts seq at
        # 1 — without this stamp the collector would read the rewound
        # stream as stale replays until it overtook the dead
        # incarnation's total, blinding it to exactly the workers
        # elastic restarts
        self.ident = f"{self.worker}~{os.urandom(4).hex()}"
        self.sent = 0
        self.dropped = 0
        self.send_errors = 0
        #: newest flight-event seq confirmed delivered — the delta
        #: cursor ``local_payload(since_seq=...)`` resumes from (a
        #: dropped push is re-shipped next interval; the collector's
        #: per-event seq dedup absorbs any overlap)
        self.flight_seq_sent = 0
        self._sock: Optional[socket.socket] = None
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="collector-push")
        self._thread.start()

    def push(self, payload: dict) -> bool:
        """Enqueue one payload; returns False when it was dropped
        (queue full or client stopped) — callers never wait."""
        monitor.stat_add("collector_pushes_total")
        if self._stop.is_set():
            self._drop()
            return False
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        item = {"op": "report", "worker": self.worker, "role": self.role,
                "ident": self.ident, "seq": seq, "time": time.time(),
                "payload": payload}
        try:
            self._q.put_nowait(item)
            return True
        except queue.Full:
            self._drop()
            return False

    def _drop(self):
        with self._seq_lock:
            self.dropped += 1
        monitor.stat_add("collector_dropped_total")

    def _close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _send_one(self, item: dict):
        chaos.fault_point("collector.rpc",  # pta: disable=PTA301 (fire-and-forget by contract: a failed push is dropped and counted, never retried or escalated into the observed process)
                          meta={"endpoint": self.endpoint,
                                "seq": item["seq"]})
        if self._sock is None:  # pta: disable=PTA404 (sender-thread-only state: _send_one/_close run exclusively on the collector-push thread, so the lazy redial is single-threaded)
            host, port = self.endpoint.rsplit(":", 1)
            self._sock = socket.create_connection(
                (host, int(port)), timeout=self.timeout)
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
        _send(self._sock, item)
        reply = _recv(self._sock)
        if not reply.get("ok", False):
            raise ConnectionError(
                f"collector rejected report: {reply.get('error')}")
        self.sent += 1
        last = item["payload"].get("flight_last_seq")
        if isinstance(last, int) and last > self.flight_seq_sent:
            self.flight_seq_sent = last

    def _drain(self):
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    self._close()
                    return
                continue
            try:
                self._send_one(item)
            except (chaos.InjectedFault, ConnectionError, OSError,
                    struct.error, ValueError):
                self._close()
                self.send_errors += 1
                self._drop()
            finally:
                self._q.task_done()

    def stop(self, timeout: float = 2.0):
        """Stop the sender (best-effort final drain, bounded by
        ``timeout`` — a dead collector cannot wedge shutdown; the
        daemon thread is abandoned past the deadline)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)


# ---------------------------------------------------------------------------
# server: aggregation + cross-worker detection
# ---------------------------------------------------------------------------

class _WorkerState:
    """Everything the collector remembers about one reporting process."""

    __slots__ = ("role", "ident", "incarnations", "last_seq", "reports",
                 "gaps", "stale", "first_ts", "last_ts", "stats",
                 "hists", "spans", "flight_kind_totals", "flight_seen",
                 "step_count", "step_sum", "interval_means",
                 "straggler_score", "straggler", "detector_anomalies",
                 "incidents")

    def __init__(self, role: str, window: int):
        self.role = role
        self.ident = None        # per-incarnation stamp (restart detect)
        self.incarnations = 0
        self.last_seq = 0
        self.reports = 0
        self.gaps = 0            # pushes the process sent that never
        self.stale = 0           # arrived (seq holes = drops visible
        self.first_ts = None     # server-side, ack-free)
        self.last_ts = None
        self.stats: Dict[str, Any] = {}
        self.hists: Dict[str, dict] = {}
        self.spans: List[dict] = []
        self.flight_kind_totals: Dict[str, int] = {}
        self.flight_seen = 0
        self.step_count = 0
        self.step_sum = 0.0
        self.interval_means: deque = deque(maxlen=window)
        self.straggler_score = 1.0
        self.straggler = False
        self.detector_anomalies = 0
        self.incidents: Dict[int, dict] = {}  # id → notice (dedup'd)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: "CollectorServer" = self.server.collector  # type: ignore
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                header = _recv(sock)
            except (ConnectionError, OSError, struct.error, ValueError):
                return
            try:
                reply = srv._dispatch(header)
            except Exception as e:  # noqa: BLE001 — serve every peer
                reply = {"ok": False, "error": repr(e)}
            try:
                _send(sock, reply)
            except OSError:
                return
            if header.get("op") == "shutdown":
                return


class _TcpServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class CollectorServer:
    """The central telemetry collector: aggregates per-process reports
    into one cluster view and runs the existing ``health.Detector``
    ACROSS workers (see module docstring).

    ``on_straggler(scores: Dict[str, float], flagged: List[str])`` is
    invoked whenever the flagged set changes — the hook ``launch``
    wires to :meth:`ElasticAgent.note_stragglers
    <paddle_tpu.distributed.elastic.ElasticAgent.note_stragglers>`, so
    the agent that today only sees hangs also sees stragglers.

    Deterministic: aggregation and scoring depend only on the payload
    sequence; the injectable ``clock`` stamps views, never gates
    anything."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 straggler_ratio: Optional[float] = None,
                 window: int = 8, flight_capacity: int = 1024,
                 worker_ttl: float = 60.0,
                 ledger_path: Optional[str] = None,
                 on_straggler: Optional[Callable] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.straggler_ratio = float(flag("collector_straggler_ratio")) \
            if straggler_ratio is None else float(straggler_ratio)
        self.window = int(window)
        # a worker silent for this long leaves the straggler scoring
        # peer set (its frozen step mean must not pollute the
        # leave-one-out median after a crash/shrink) and is marked
        # expired in the view; rows are kept for the post-mortem
        self.worker_ttl = float(worker_ttl)
        self.ledger_path = ledger_path
        self.on_straggler = on_straggler
        self.clock = clock or time.time
        self._lock = locks.lock("collector.server.state")
        self._workers: Dict[str, _WorkerState] = {}
        self._tables: Dict[str, dict] = {}
        self._flight: deque = deque(maxlen=max(1, int(flight_capacity)))
        self._flight_kind_totals: Dict[str, int] = {}
        self._detectors: Dict[str, Any] = {}
        self.reports_total = 0
        self._tcp = _TcpServer((host, port), _Handler)
        self._tcp.collector = self  # type: ignore
        self.host, self.port = self._tcp.server_address
        self.endpoint = f"{self.host}:{self.port}"
        self._thread: Optional[threading.Thread] = None
        # lifecycle latch: _serving is flipped from the owner thread
        # AND from the dispatch thread a remote `shutdown` op spawns —
        # the check-and-clear must be atomic or two racing shutdowns
        # both call BaseServer.shutdown() (PTA403/404, the bug class of
        # the original shutdown-on-never-started-server deadlock)
        self._life_lock = locks.lock("collector.server.lifecycle")
        self._serving = False

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "CollectorServer":
        with self._life_lock:
            self._serving = True
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True,
                                        name="collector-server")
        self._thread.start()
        return self

    def serve_forever(self):
        with self._life_lock:
            self._serving = True
        self._tcp.serve_forever()

    def shutdown(self):
        # BaseServer.shutdown() waits for a serve_forever loop to
        # acknowledge — on a server that was never started it would
        # wait forever, and an aggregation-only CollectorServer (tests
        # drive _handle_report directly) is legitimate.  The atomic
        # swap also makes concurrent shutdowns idempotent: exactly one
        # caller sees serving=True and stops the loop.
        with self._life_lock:
            serving, self._serving = self._serving, False
        if serving:
            self._tcp.shutdown()
        self._tcp.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- dispatch -----------------------------------------------------------
    def _dispatch(self, header: dict):
        op = header.get("op")
        if op == "hello":
            # carries the collector's time like the PS hello, so a
            # pusher could clock-sync against it the same way
            return {"ok": True, "service": "collector",
                    "time": time.time()}
        if op == "report":
            return self._handle_report(header)
        if op == "view":
            return {"ok": True, "view": self.view()}
        if op == "capture":
            rec, committed = self.capture_record(
                label=header.get("label"))
            return {"ok": True, "record": rec, "committed": committed}
        if op == "shutdown":
            threading.Thread(target=self.shutdown, daemon=True).start()
            return {"ok": True}
        return {"ok": False, "error": f"unknown collector op {op!r}"}

    # -- aggregation --------------------------------------------------------
    def _handle_report(self, header: dict):
        worker = str(header.get("worker") or "?")
        role = str(header.get("role") or "worker")
        ident = header.get("ident")
        seq = int(header.get("seq") or 0)
        payload = header.get("payload") or {}
        now = self.clock()
        with self._lock:
            st = self._workers.get(worker)
            if st is None:
                st = self._workers[worker] = _WorkerState(role,
                                                          self.window)
            st.role = role
            if ident is not None and ident != st.ident:
                # a NEW incarnation of this worker (elastic restart):
                # its push seq, cumulative step counters, and flight
                # seq all rewound — reset the cursors so the restarted
                # worker reports immediately instead of being read as
                # stale until it overtakes its dead predecessor.  The
                # windowed interval means survive: they are this worker
                # SLOT's history, and a bounded window ages them out
                st.ident = ident
                st.incarnations += 1
                st.last_seq = 0
                st.step_count = 0
                st.step_sum = 0.0
                st.flight_seen = 0
            if seq and seq <= st.last_seq:
                # a replayed/reordered push within one incarnation (an
                # identless legacy client restart is also read as
                # stale until it overtakes)
                st.stale += 1
                return {"ok": True, "stale": True}
            if seq:
                if st.last_seq:
                    st.gaps += max(0, seq - st.last_seq - 1)
                st.last_seq = seq
            st.reports += 1
            self.reports_total += 1
            st.first_ts = st.first_ts if st.first_ts is not None else now
            st.last_ts = now
            st.stats = dict(payload.get("stats") or {})
            st.hists = dict(payload.get("hists") or {})
            if payload.get("spans"):
                st.spans = list(payload["spans"])
            for kind, n in (payload.get("flight_events") or {}).items():
                st.flight_kind_totals[kind] = int(n)
            # flight delta merge: per-event per-process seq dedup, so a
            # re-shipped overlap (the pusher only advances its cursor
            # on a confirmed send) lands exactly once
            for ev in payload.get("flight") or []:
                es = int(ev.get("seq") or 0)
                if es and es <= st.flight_seen:
                    continue
                st.flight_seen = max(st.flight_seen, es)
                merged = dict(ev)
                merged["worker"] = worker
                # incarnation rides along so the view merge keeps each
                # restart's (rewound) seq stream separate
                merged["inc"] = st.incarnations
                self._flight.append(merged)
                kind = str(ev.get("kind", "?"))
                self._flight_kind_totals[kind] = \
                    self._flight_kind_totals.get(kind, 0) + 1
            # incident notices: the client ships its cumulative bounded
            # queue whole each push — dedup by id so a re-shipped
            # notice lands exactly once and a dropped push loses none
            for n in payload.get("incidents") or []:
                try:
                    nid = int(n.get("id"))
                except (TypeError, ValueError):
                    continue
                if nid not in st.incidents:
                    st.incidents[nid] = dict(n, worker=worker)
            # PS table telemetry (server roles): keep the LATEST
            # cumulative snapshot per shard — summing reports would
            # double-count
            for tname, t in (payload.get("tables") or {}).items():
                agg = self._tables.setdefault(tname, {"by_shard": {}})
                agg["by_shard"][worker] = dict(t)
            # per-interval step mean: the collector diffs consecutive
            # cumulative train_step_ms (count, sum) pairs
            h = st.hists.get("train_step_ms")
            interval_mean = None
            if h and h.get("count"):
                c, s = int(h["count"]), float(h.get("sum") or 0.0)
                if c > st.step_count:
                    interval_mean = (s - st.step_sum) / (c - st.step_count)
                    st.step_count, st.step_sum = c, s
                    st.interval_means.append(interval_mean)
            changed = self._rescore_locked(worker, interval_mean, now)
            scores = {w: ws.straggler_score
                      for w, ws in self._workers.items()
                      if ws.interval_means}
            flagged = sorted(w for w, ws in self._workers.items()
                             if ws.straggler)
        if changed and self.on_straggler is not None:
            try:
                self.on_straggler(scores, flagged)
            except Exception:  # noqa: BLE001 — a broken hook must not
                pass           # take the collector down
        return {"ok": True}

    def _expired_locked(self, st: _WorkerState, now: float) -> bool:
        return st.last_ts is not None and \
            now - st.last_ts > self.worker_ttl

    def _rescore_locked(self, worker: str,
                        interval_mean: Optional[float],
                        now: float) -> bool:
        """Re-derive straggler scores after one report (lock held).
        Score = the worker's windowed interval mean over the LEAVE-ONE-
        OUT median of its peers' — robust at any world size, and a
        2-worker cluster (the minimal acceptance shape) still separates
        cleanly where a pooled median would sit between the two.
        Workers silent past ``worker_ttl`` drop out of the peer set
        (and lose any straggler flag — dead is the hang watchdog's
        department, not this one's).  Returns True when the flagged set
        changed."""
        changed = False
        means = {}
        for w, ws in self._workers.items():
            if not ws.interval_means:
                continue
            if self._expired_locked(ws, now):
                if ws.straggler:
                    ws.straggler = False
                    changed = True
                    flight.record("collector.straggler", severity="info",
                                  worker=w, expired=True, flagged=False)
                continue
            means[w] = sum(ws.interval_means) / len(ws.interval_means)
        if len(means) >= 2:
            for w, m in means.items():
                ws = self._workers[w]
                peers = sorted(v for pw, v in means.items() if pw != w)
                # LOWER median: with an even peer count the averaged
                # median would be dragged up by a slow peer, deflating
                # a clean worker's score below 1.0 and (in a 3-worker
                # cluster) halving the straggler's — biasing the
                # denominator toward the fast half errs toward
                # flagging, never toward hiding
                med = peers[(len(peers) - 1) // 2]
                score = m / max(med, 1e-9)
                ws.straggler_score = score
                # don't flag off a single interval: a worker's first
                # report carries its compile-inflated first step, and a
                # one-sample flag would flap every fresh joiner through
                # the ElasticAgent hook (score is still reported)
                flagged = score >= self.straggler_ratio and \
                    len(ws.interval_means) >= 2
                if flagged != ws.straggler:
                    ws.straggler = flagged
                    changed = True
                    flight.record("collector.straggler",
                                  severity="warn" if flagged else "info",
                                  worker=w, score=round(score, 3),
                                  flagged=flagged)
                monitor.stat_set(f"cluster_straggler_score[{w}]",
                                 round(score, 4))
        # cross-worker detection with the EXISTING health.Detector: one
        # detector per worker over its own interval-mean stream catches
        # a rank *becoming* slow (the mid-run latency injection) even
        # before the cross-sectional ratio crosses the flag threshold
        if interval_mean is not None:
            det = self._detectors.get(worker)
            if det is None:
                from paddle_tpu.framework.health import Detector
                det = self._detectors[worker] = Detector(
                    f"cluster_step_ms[{worker}]", warmup=4, window=32,
                    rel_floor=0.5, min_mad=5.0, clock=self.clock)
            a = det.update(interval_mean)
            if a is not None:
                ws = self._workers[worker]
                ws.detector_anomalies += 1
                monitor.stat_add("cluster_step_anomalies_total")
                flight.record("collector.step_anomaly", severity="warn",
                              worker=worker,
                              value=round(a.value, 4),
                              median=round(a.median, 4),
                              z=round(a.z, 2) if a.z == a.z else "inf")
        return changed

    # -- views --------------------------------------------------------------
    @staticmethod
    def _rpc_p99(hists: Dict[str, dict]) -> Optional[float]:
        p99s = [float(h.get("p99") or 0.0) for n, h in hists.items()
                if n.startswith("ps_client_rpc_ms_") and h.get("count")]
        return max(p99s) if p99s else None

    def view(self) -> dict:
        """One JSON-able cluster snapshot — what the ``view`` op
        returns and ``cluster_top`` renders."""
        now = self.clock()
        with self._lock:
            workers = {}
            for w, st in sorted(self._workers.items()):
                h = st.hists.get("train_step_ms") or {}
                expired = self._expired_locked(st, now)
                row = {
                    "role": st.role,
                    "reports": st.reports,
                    "last_seq": st.last_seq,
                    "incarnations": st.incarnations,
                    "gaps": st.gaps,
                    "age_s": round(now - st.last_ts, 3)
                    if st.last_ts is not None else None,
                    "expired": expired,
                    "steps_total": int(h.get("count") or 0),
                    "step_p50_ms": h.get("p50"),
                    "step_p99_ms": h.get("p99"),
                    "step_interval_mean_ms": round(
                        sum(st.interval_means) / len(st.interval_means),
                        4) if st.interval_means else None,
                    "input_stall_pct": st.stats.get("input_stall_pct"),
                    "ps_rpc_p99_ms": self._rpc_p99(st.hists),
                    "anomalies_total": int(
                        st.stats.get("health_anomalies_total") or 0),
                    "flight_total": sum(st.flight_kind_totals.values()),
                    "drops_reported": int(
                        st.stats.get("collector_dropped_total") or 0),
                    # expiry re-evaluated at READ time: a straggler
                    # that died (and took the cluster's reports with
                    # it) must not stay flagged in a view/capture taken
                    # hours later — dead is the hang watchdog's
                    # department
                    "straggler": st.straggler and not expired,
                    "straggler_score": round(st.straggler_score, 4),
                    "detector_anomalies": st.detector_anomalies,
                    "incidents_total": len(st.incidents),
                }
                workers[w] = row
            tables = {tname: aggregate_table_shards(agg["by_shard"])
                      for tname, agg in sorted(self._tables.items())}
            incidents = sorted(
                (dict(n) for st in self._workers.values()
                 for n in st.incidents.values()),
                key=lambda n: (str(n.get("worker")),
                               int(n.get("id") or 0)))
            flight_rows = merge_flight_events(
                self._group_flight_locked())
            return {
                "schema_version": VIEW_SCHEMA_VERSION,
                "ts": now,
                "endpoint": self.endpoint,
                "reports_total": self.reports_total,
                "workers": workers,
                "tables": tables,
                "stragglers": sorted(
                    w for w, row in workers.items() if row["straggler"]),
                "straggler_ratio": self.straggler_ratio,
                "flight_kind_totals": dict(self._flight_kind_totals),
                "flight": flight_rows[-64:],
                "incidents": incidents[-64:],
            }

    def _group_flight_locked(self) -> Dict[tuple, List[dict]]:
        groups: Dict[tuple, List[dict]] = {}
        for ev in self._flight:
            key = (ev.get("worker", "?"), ev.get("inc", 0))
            groups.setdefault(key, []).append(ev)
        return groups

    def straggler_report(self) -> dict:
        """The scores/flags alone (what tests and the ElasticAgent hook
        consume without a full view); expiry re-checked at read time
        like :meth:`view`."""
        now = self.clock()
        with self._lock:
            return {
                "scores": {w: round(st.straggler_score, 4)
                           for w, st in self._workers.items()
                           if st.interval_means},
                "stragglers": sorted(
                    w for w, st in self._workers.items()
                    if st.straggler and
                    not self._expired_locked(st, now)),
                "ratio": self.straggler_ratio,
            }

    # -- cluster-level run record ------------------------------------------
    def capture_record(self, label: Optional[str] = None):
        """Assemble a cluster-granularity RunRecord — the summary
        series ``perf_report compare`` gates over is CLUSTER-level (max
        step p99 across workers, max straggler score, straggler count,
        worst RPC p99, summed anomalies, summed push gaps) and the
        ``cluster`` section names every worker and flagged straggler.
        Appends to ``ledger_path`` when configured; returns
        ``(record, committed)``."""
        from paddle_tpu.framework import runlog
        view = self.view()
        rows = view["workers"].values()

        def _agg(fn, key, dflt=None):
            vals = [r[key] for r in rows if r.get(key) is not None]
            return fn(vals) if vals else dflt

        summary: Dict[str, Any] = {}
        for key, out in (("step_p99_ms", "cluster_step_p99_ms_max"),
                         ("ps_rpc_p99_ms", "cluster_ps_rpc_p99_ms"),
                         ("input_stall_pct",
                          "cluster_input_stall_pct_max")):
            v = _agg(max, key)
            if v is not None:
                summary[out] = float(v)
        scores = [r["straggler_score"] for r in rows
                  if r.get("step_interval_mean_ms") is not None]
        if scores:
            summary["cluster_step_skew"] = float(max(scores))
        summary["cluster_straggler_count"] = len(view["stragglers"])
        summary["cluster_anomalies_total"] = float(
            sum(r["anomalies_total"] for r in rows))
        summary["cluster_report_gaps_total"] = float(
            sum(r["gaps"] for r in rows))
        rec = runlog.capture(
            "cluster", label=label or "cluster",
            include_snapshot=False,
            extra={"summary": summary,
                   "cluster": {"workers": view["workers"],
                               "stragglers": view["stragglers"],
                               "straggler_ratio": view["straggler_ratio"],
                               "tables": view["tables"]}})
        committed = False
        if self.ledger_path:
            committed = runlog.RunLedger(self.ledger_path).append(rec)
        return rec, committed


# collector-plane metric help texts (the # HELP satellite)
monitor.describe("cluster_straggler_score",
                 "per-worker step-time skew vs the leave-one-out peer "
                 "median (collector-side gauge)")
monitor.describe("cluster_step_anomalies_total",
                 "cross-worker step-time Detector anomalies seen by "
                 "the collector")
monitor.describe("ps_server_table_pulls",
                 "pull RPCs served per table (per-shard gauge)")
monitor.describe("ps_server_table_pushes",
                 "push RPCs applied per table (per-shard gauge)")


# ---------------------------------------------------------------------------
# process wiring
# ---------------------------------------------------------------------------

def auto_reporter(role: Optional[str] = None, worker: Optional[str] = None,
                  interval: Optional[float] = None,
                  path: Optional[str] = None, payload_extra=None):
    """Start a push-mode ``MetricsReporter`` against the configured
    collector endpoint (``PADDLE_COLLECTOR_ENDPOINT`` env — the
    launcher sets it for every child, server and trainer roles alike —
    or ``FLAGS_collector_endpoint``).  Returns the started reporter, or
    None when no endpoint is configured — the one-liner any process
    drops into its startup.  ``payload_extra`` (a callable returning a
    dict) merges producer-specific sections into every push (the PS
    server's per-table telemetry)."""
    ep = collector_endpoint()
    if ep is None:
        return None
    from paddle_tpu.framework.observability import MetricsReporter
    return MetricsReporter(
        path,
        interval=float(flag("collector_interval"))
        if interval is None else interval,
        collector=ep, worker=worker, role=role,
        payload_extra=payload_extra).start()


def serve(port: int = 0, host: str = "127.0.0.1",
          ledger_path: Optional[str] = None, announce=print):
    """Blocking standalone collector entry (the launcher runs it
    in-process instead via ``--collector``)."""
    srv = CollectorServer(host=host, port=port, ledger_path=ledger_path)
    announce(f"COLLECTOR_READY {srv.endpoint}", flush=True)
    srv.serve_forever()


def _main():
    import argparse
    ap = argparse.ArgumentParser(
        description="paddle_tpu central telemetry collector")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--ledger", default=None,
                    help="append cluster RunRecords here on 'capture'")
    a = ap.parse_args()
    serve(a.port, a.host, ledger_path=a.ledger)


if __name__ == "__main__":
    _main()
