"""Model encryption — AES cipher + key utilities, no external deps.

Reference: paddle/fluid/framework/io/crypto/{aes_cipher.cc, cipher.cc,
cipher_utils.cc} (CryptoPP-backed AES exposed through pybind as
``core.Cipher``/``CipherFactory``/``CipherUtils``).  The environment has
no crypto library, so the AES-128/192/256 block cipher is implemented
directly (FIPS-197 tables, key-answer-tested) and runs in CTR mode with
an HMAC-SHA256 tag (encrypt-then-MAC) — authenticated encryption serving
the reference's AES/GCM role.  File format:
``b"PTAE1" | 16-byte nonce | ciphertext | 32-byte hmac``.

API shape follows the reference: ``CipherFactory.create_cipher()`` ->
cipher with ``encrypt/decrypt/encrypt_to_file/decrypt_from_file``, and
``CipherUtils.gen_key / gen_key_to_file / read_key_from_file``.
"""
from __future__ import annotations

import hmac
import hashlib
import os
import struct

__all__ = ["AESCipher", "CipherFactory", "CipherUtils"]

_SBOX = [
    0x63, 0x7c, 0x77, 0x7b, 0xf2, 0x6b, 0x6f, 0xc5, 0x30, 0x01, 0x67,
    0x2b, 0xfe, 0xd7, 0xab, 0x76, 0xca, 0x82, 0xc9, 0x7d, 0xfa, 0x59,
    0x47, 0xf0, 0xad, 0xd4, 0xa2, 0xaf, 0x9c, 0xa4, 0x72, 0xc0, 0xb7,
    0xfd, 0x93, 0x26, 0x36, 0x3f, 0xf7, 0xcc, 0x34, 0xa5, 0xe5, 0xf1,
    0x71, 0xd8, 0x31, 0x15, 0x04, 0xc7, 0x23, 0xc3, 0x18, 0x96, 0x05,
    0x9a, 0x07, 0x12, 0x80, 0xe2, 0xeb, 0x27, 0xb2, 0x75, 0x09, 0x83,
    0x2c, 0x1a, 0x1b, 0x6e, 0x5a, 0xa0, 0x52, 0x3b, 0xd6, 0xb3, 0x29,
    0xe3, 0x2f, 0x84, 0x53, 0xd1, 0x00, 0xed, 0x20, 0xfc, 0xb1, 0x5b,
    0x6a, 0xcb, 0xbe, 0x39, 0x4a, 0x4c, 0x58, 0xcf, 0xd0, 0xef, 0xaa,
    0xfb, 0x43, 0x4d, 0x33, 0x85, 0x45, 0xf9, 0x02, 0x7f, 0x50, 0x3c,
    0x9f, 0xa8, 0x51, 0xa3, 0x40, 0x8f, 0x92, 0x9d, 0x38, 0xf5, 0xbc,
    0xb6, 0xda, 0x21, 0x10, 0xff, 0xf3, 0xd2, 0xcd, 0x0c, 0x13, 0xec,
    0x5f, 0x97, 0x44, 0x17, 0xc4, 0xa7, 0x7e, 0x3d, 0x64, 0x5d, 0x19,
    0x73, 0x60, 0x81, 0x4f, 0xdc, 0x22, 0x2a, 0x90, 0x88, 0x46, 0xee,
    0xb8, 0x14, 0xde, 0x5e, 0x0b, 0xdb, 0xe0, 0x32, 0x3a, 0x0a, 0x49,
    0x06, 0x24, 0x5c, 0xc2, 0xd3, 0xac, 0x62, 0x91, 0x95, 0xe4, 0x79,
    0xe7, 0xc8, 0x37, 0x6d, 0x8d, 0xd5, 0x4e, 0xa9, 0x6c, 0x56, 0xf4,
    0xea, 0x65, 0x7a, 0xae, 0x08, 0xba, 0x78, 0x25, 0x2e, 0x1c, 0xa6,
    0xb4, 0xc6, 0xe8, 0xdd, 0x74, 0x1f, 0x4b, 0xbd, 0x8b, 0x8a, 0x70,
    0x3e, 0xb5, 0x66, 0x48, 0x03, 0xf6, 0x0e, 0x61, 0x35, 0x57, 0xb9,
    0x86, 0xc1, 0x1d, 0x9e, 0xe1, 0xf8, 0x98, 0x11, 0x69, 0xd9, 0x8e,
    0x94, 0x9b, 0x1e, 0x87, 0xe9, 0xce, 0x55, 0x28, 0xdf, 0x8c, 0xa1,
    0x89, 0x0d, 0xbf, 0xe6, 0x42, 0x68, 0x41, 0x99, 0x2d, 0x0f, 0xb0,
    0x54, 0xbb, 0x16,
]

_RCON = [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1b, 0x36,
         0x6c, 0xd8, 0xab, 0x4d]


def _xtime(a: int) -> int:
    a <<= 1
    return (a ^ 0x1b) & 0xff if a & 0x100 else a


# precompute GF(2^8) multiply-by-2 and -by-3 tables for MixColumns
_MUL2 = [_xtime(i) for i in range(256)]
_MUL3 = [_xtime(i) ^ i for i in range(256)]


def _expand_key(key: bytes):
    nk = len(key) // 4
    nr = {4: 10, 6: 12, 8: 14}[nk]
    w = [list(key[4 * i:4 * i + 4]) for i in range(nk)]
    for i in range(nk, 4 * (nr + 1)):
        t = list(w[i - 1])
        if i % nk == 0:
            t = t[1:] + t[:1]
            t = [_SBOX[b] for b in t]
            t[0] ^= _RCON[i // nk - 1]
        elif nk > 6 and i % nk == 4:
            t = [_SBOX[b] for b in t]
        w.append([a ^ b for a, b in zip(w[i - nk], t)])
    # round keys as flat 16-byte lists
    return [sum(w[4 * r:4 * r + 4], []) for r in range(nr + 1)], nr


def _encrypt_block(state: list, round_keys, nr: int) -> bytes:
    s = [b ^ k for b, k in zip(state, round_keys[0])]
    for rnd in range(1, nr):
        s = [_SBOX[b] for b in s]
        # ShiftRows on column-major state: byte i lives at 4*col+row;
        # row r rotates left by r
        s = [s[(i + 4 * (i % 4)) % 16] for i in range(16)]
        ns = [0] * 16
        for c in range(4):
            a0, a1, a2, a3 = s[4 * c:4 * c + 4]
            ns[4 * c + 0] = _MUL2[a0] ^ _MUL3[a1] ^ a2 ^ a3
            ns[4 * c + 1] = a0 ^ _MUL2[a1] ^ _MUL3[a2] ^ a3
            ns[4 * c + 2] = a0 ^ a1 ^ _MUL2[a2] ^ _MUL3[a3]
            ns[4 * c + 3] = _MUL3[a0] ^ a1 ^ a2 ^ _MUL2[a3]
        s = [b ^ k for b, k in zip(ns, round_keys[rnd])]
    s = [_SBOX[b] for b in s]
    s = [s[(i + 4 * (i % 4)) % 16] for i in range(16)]
    return bytes(b ^ k for b, k in zip(s, round_keys[nr]))


_MAGIC = b"PTAE1"


class AESCipher:
    """AES-CTR + HMAC-SHA256 (reference AESCipher role)."""

    def __init__(self, key_len: int = 16):
        if key_len not in (16, 24, 32):
            raise ValueError("AES key length must be 16/24/32 bytes")
        self._key_len = key_len

    def _keys(self, key: bytes):
        if len(key) != self._key_len:
            raise ValueError(
                f"expected a {self._key_len}-byte key, got {len(key)}")
        enc_key = hashlib.sha256(b"enc" + key).digest()[:self._key_len]
        mac_key = hashlib.sha256(b"mac" + key).digest()
        return enc_key, mac_key

    def _ctr_stream(self, enc_key: bytes, nonce: bytes, n: int) -> bytes:
        rks, nr = _expand_key(enc_key)
        out = bytearray()
        hi, lo = struct.unpack(">QQ", nonce)
        for i in range((n + 15) // 16):
            ctr = struct.pack(">QQ", hi, (lo + i) & 0xFFFFFFFFFFFFFFFF)
            out += _encrypt_block(list(ctr), rks, nr)
        return bytes(out[:n])

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        if isinstance(plaintext, str):
            plaintext = plaintext.encode()
        enc_key, mac_key = self._keys(key)
        nonce = os.urandom(16)
        ct = bytes(p ^ s for p, s in zip(
            plaintext, self._ctr_stream(enc_key, nonce, len(plaintext))))
        tag = hmac.new(mac_key, _MAGIC + nonce + ct,
                       hashlib.sha256).digest()
        return _MAGIC + nonce + ct + tag

    def decrypt(self, blob: bytes, key: bytes) -> bytes:
        enc_key, mac_key = self._keys(key)
        if len(blob) < len(_MAGIC) + 16 + 32 or \
                not blob.startswith(_MAGIC):
            raise ValueError("not a paddle_tpu-encrypted payload")
        nonce = blob[len(_MAGIC):len(_MAGIC) + 16]
        ct, tag = blob[len(_MAGIC) + 16:-32], blob[-32:]
        want = hmac.new(mac_key, _MAGIC + nonce + ct,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise ValueError("authentication failed: wrong key or "
                             "corrupted file")
        return bytes(c ^ s for c, s in zip(
            ct, self._ctr_stream(enc_key, nonce, len(ct))))

    def encrypt_to_file(self, plaintext: bytes, key: bytes, path: str):
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key: bytes, path: str) -> bytes:
        with open(path, "rb") as f:
            return self.decrypt(f.read(), key)


class CipherFactory:
    @staticmethod
    def create_cipher(config_file: str = None) -> AESCipher:
        # the reference reads a CryptoPP property file; the only knob that
        # survives is the key length
        key_len = 16
        if config_file and os.path.exists(config_file):
            with open(config_file) as f:
                for line in f:
                    if "keysize" in line.lower().replace("_", ""):
                        key_len = int(line.split("=")[-1].strip()) // 8 \
                            if int(line.split("=")[-1].strip()) > 32 \
                            else int(line.split("=")[-1].strip())
        return AESCipher(key_len)


class CipherUtils:
    @staticmethod
    def gen_key(length_bits: int = 128) -> bytes:
        if length_bits not in (128, 192, 256):
            raise ValueError("key length must be 128/192/256 bits")
        return os.urandom(length_bits // 8)

    @staticmethod
    def gen_key_to_file(length_bits: int, path: str) -> bytes:
        key = CipherUtils.gen_key(length_bits)
        with open(path, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()


def _aes_ecb_encrypt_block(key: bytes, block: bytes) -> bytes:
    """Raw single-block AES (test hook for FIPS-197 known answers)."""
    rks, nr = _expand_key(key)
    return _encrypt_block(list(block), rks, nr)
