"""Pallas kernel analysis — the fifth front end of the program analyzer.

Hand-written kernels are where tiling off-by-ones, masked-tail bugs and
silent low-precision accumulation live, and none of the existing front
ends can see them: the jaxpr passes see one opaque ``pallas_call``
equation, the AST lint sees ordinary Python.  This front end extracts a
**kernel model** from every ``pallas_call`` site reached by a traced
builder — the grid, each operand's BlockSpec block shape and index map,
the kernel body's AST — and checks the invariants Mosaic will not check
for you (out-of-bounds blocks read garbage and clipped writes silently
drop data; nothing faults).

Model extraction is capture-based: :func:`trace_kernels` patches
``pl.pallas_call`` and abstractly evaluates the builder
(``jax.eval_shape`` — no FLOPs, no device).  Index maps are plain
arithmetic lambdas over grid coordinates, so the passes evaluate them on
concrete grid points to decide coverage and write-revisit order
analytically.  The kernel body is recovered via ``inspect`` and analyzed
with the PTA2xx taint machinery re-scoped to kernel refs and
``program_id``.

Rules (stable IDs; see diagnostics.RULES):

========  ==============================================================
PTA601    grid/block tail bug: the grid's coverage (max block index ×
          block) stops short of an output dim (tail rows never
          written), or an input block overruns its dim with no iota
          tail mask anywhere in the kernel body (garbage read)
PTA602    low-precision accumulation: a dot/``@`` in a kernel touching
          bf16/f16 operands without ``preferred_element_type``, or a
          ``+=`` carry into a half-precision ref
PTA603    output-block race: the output index_map ignores a grid axis
          that is not innermost (revisits of one block interleave with
          other blocks — last writer wins), or maps two distinct grid
          points onto one block (non-injective)
PTA604    tail mask off by the block origin: an iota compared against a
          length without a ``program_id``-derived origin term while the
          grid has more than one block — every block but the first is
          mis-masked
PTA605    analytic VMEM overcommit: 2× (double-buffered) in/out block
          footprints + scratch exceed ``FLAGS_pallas_vmem_budget_kb``
PTA606    non-static kernel control flow: Python ``if``/``while``/
          ``for`` on a value derived from a ref load or ``program_id``
          — trace-time concretization error waiting to happen
========  ==============================================================

Runtime half: ``ops/pallas/verify.py`` — the ``FLAGS_pallas_verify``
differential oracle names a divergent operand with the SAME
``<name>.<operand>`` label these passes use (see
:func:`operand_labels`).

Suppression: ``# pta: disable=PTA601`` on any line of the
``pallas_call(...)`` call header suppresses call-anchored rules
(601/603/605) there; body-anchored rules (602/604/606) take the pragma
on the offending kernel-body line.  ``# pta: disable-file=`` in the
first 10 lines works as everywhere else.
"""
from __future__ import annotations

import ast
import functools
import inspect
import re
import textwrap
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.framework.analysis.ast_passes import _last_name, _Taint
from paddle_tpu.framework.analysis.diagnostics import (
    Diagnostic, Report, Severity, parse_suppressions, register_rule)

__all__ = ["KernelModel", "OperandModel", "trace_kernels",
           "analyze_kernels", "operand_labels"]

register_rule("PTA601", "grid/block tail not covered or unmasked",
              Severity.ERROR, "pallas")
register_rule("PTA602", "low-precision accumulation in kernel",
              Severity.WARNING, "pallas")
register_rule("PTA603", "output-block race across grid axes",
              Severity.ERROR, "pallas")
register_rule("PTA604", "tail mask missing its block origin",
              Severity.ERROR, "pallas")
register_rule("PTA605", "analytic VMEM overcommit", Severity.WARNING,
              "pallas")
register_rule("PTA606", "non-static python control flow in kernel",
              Severity.ERROR, "pallas")

# how many grid points the analytic passes will enumerate exhaustively;
# larger grids fall back to per-axis boundary sampling (index maps are
# affine in practice, so boundaries decide coverage and dependence)
_GRID_CAP = 4096
# names of f32-accumulating dot helpers the PTA602 pass trusts (the
# shared ops/pallas/common.py wrapper sets preferred_element_type)
_SAFE_DOT_HELPERS = ("dot_nt",)
_DOT_NAMES = {"dot", "dot_general", "matmul", "tensordot", "einsum"}
_IOTA_NAMES = {"iota", "broadcasted_iota"}
_PID_NAMES = {"program_id", "num_programs"}


# ---------------------------------------------------------------------------
# kernel model
# ---------------------------------------------------------------------------


@dataclass
class OperandModel:
    """One pallas_call operand: shape/dtype + its BlockSpec."""
    label: str                         # param-derived short name
    kind: str                          # "in" | "out"
    shape: Tuple[int, ...]
    dtype: Any
    block_shape: Optional[Tuple[int, ...]]
    index_map: Optional[Any]

    def block_bytes(self) -> int:
        shape = self.block_shape or self.shape
        n = 1
        for d in shape:
            n *= int(d if d is not None else 1)
        return n * np.dtype(self.dtype).itemsize


@dataclass
class KernelModel:
    """Everything the passes know about one captured pallas_call."""
    name: str                          # "<analysis name>" or "...[i]"
    kernel_name: str
    grid: Tuple[int, ...]
    inputs: List[OperandModel]
    outputs: List[OperandModel]
    scratch: List[Tuple[Tuple[int, ...], Any]]
    call_file: Optional[str] = None
    call_line: Optional[int] = None
    body_file: Optional[str] = None
    body_tree: Optional[ast.AST] = None    # FunctionDef, real linenos
    static_kwargs: Dict[str, Any] = field(default_factory=dict)
    kernel_fn: Optional[Any] = None        # unwrapped callable (helper
    #                                        resolution via __globals__)

    @property
    def operands(self) -> List[OperandModel]:
        return self.inputs + self.outputs


def _clean_param(name: str) -> str:
    return re.sub(r"_(ref|scr|scratch)$", "", name).lstrip("_") or name


def operand_labels(model: KernelModel) -> Tuple[List[str], List[str]]:
    """(input labels, output labels) — ``<model.name>.<operand>``.

    This is the shared label vocabulary: the runtime differential oracle
    (ops/pallas/verify.py) reports its first divergent operand with the
    same strings, so a static finding and a runtime divergence point at
    one name.
    """
    return ([f"{model.name}.{op.label}" for op in model.inputs],
            [f"{model.name}.{op.label}" for op in model.outputs])


def _unwrap_kernel(kernel):
    kw: Dict[str, Any] = {}
    base = kernel
    while isinstance(base, functools.partial):
        kw.update(base.keywords or {})
        base = base.func
    return base, kw


def _kernel_body(base) -> Tuple[Optional[str], Optional[ast.AST]]:
    """(source file, FunctionDef with real line numbers) of the kernel,
    or (None, None) when the source is unrecoverable (lambdas, exec)."""
    try:
        path = inspect.getsourcefile(base)
        lines, lnum = inspect.getsourcelines(base)
        src = textwrap.dedent("".join(lines))
        tree = ast.parse(src)
        fn = next(n for n in tree.body
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)))
        ast.increment_lineno(fn, lnum - 1)
        return path, fn
    except Exception:                  # noqa: BLE001 — analysis is best-effort
        return None, None


def _param_names(body: Optional[ast.AST]) -> Optional[List[str]]:
    """Positional parameter names of the kernel def, or None for
    ``*args`` kernels (labels fall back to in0/out0/...)."""
    if body is None:
        return None
    a = body.args
    names = [p.arg for p in
             list(getattr(a, "posonlyargs", [])) + list(a.args)]
    if not names and a.vararg is not None:
        return None
    return names or None


def _spec_list(specs, n: int) -> list:
    if specs is None:
        return [None] * n
    if not isinstance(specs, (list, tuple)):
        return [specs]
    return list(specs)


def _normalize_block(spec, shape):
    if spec is None:
        return None, None
    blk = getattr(spec, "block_shape", None)
    imap = getattr(spec, "index_map", None)
    if blk is None:
        return None, imap
    return tuple(int(d) if d is not None else int(s)
                 for d, s in zip(blk, shape)), imap


def _scratch_entry(s):
    shape = tuple(int(d) for d in getattr(s, "shape", ()))
    dtype = getattr(s, "dtype", np.float32)
    return shape, dtype


# ---------------------------------------------------------------------------
# capture
# ---------------------------------------------------------------------------


def trace_kernels(fn, *args, **kwargs) -> List[KernelModel]:
    """Abstractly evaluate ``fn(*args)`` with ``pl.pallas_call`` patched
    to record a :class:`KernelModel` per call site instead of running.

    ``args`` may be arrays or ``jax.ShapeDtypeStruct``s; nothing is
    executed (``jax.eval_shape``), so shapes are free — pass the real
    model shapes, including the awkward non-divisible ones.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    captured: List[KernelModel] = []
    real = pl.pallas_call

    def fake(kernel, *, grid=None, in_specs=None, out_specs=None,
             out_shape=None, scratch_shapes=(), **kw):
        frame = inspect.currentframe().f_back
        call_file = frame.f_code.co_filename if frame else None
        call_line = frame.f_lineno if frame else None
        base, static_kw = _unwrap_kernel(kernel)
        body_file, body = _kernel_body(base)
        grid_t = (int(grid),) if isinstance(grid, int) else \
            tuple(int(g) for g in (grid or ()))

        single_out = not isinstance(out_shape, (list, tuple))
        out_structs = [out_shape] if single_out else list(out_shape)
        outspecs = _spec_list(out_specs, len(out_structs))
        scratch = [_scratch_entry(s) for s in (scratch_shapes or ())]

        def runner(*ops):
            inspecs = _spec_list(in_specs, len(ops))
            names = _param_names(body)
            n_in, n_out = len(ops), len(out_structs)
            if names and len(names) >= n_in + n_out:
                in_names = [_clean_param(n) for n in names[:n_in]]
                out_names = [_clean_param(n)
                             for n in names[n_in:n_in + n_out]]
            else:
                in_names = [f"in{i}" for i in range(n_in)]
                out_names = [f"out{i}" for i in range(n_out)]
            inputs, outputs = [], []
            for i, op in enumerate(ops):
                shape = tuple(int(d) for d in op.shape)
                blk, imap = _normalize_block(
                    inspecs[i] if i < len(inspecs) else None, shape)
                inputs.append(OperandModel(in_names[i], "in", shape,
                                           op.dtype, blk, imap))
            for i, st in enumerate(out_structs):
                shape = tuple(int(d) for d in st.shape)
                blk, imap = _normalize_block(
                    outspecs[i] if i < len(outspecs) else None, shape)
                outputs.append(OperandModel(out_names[i], "out", shape,
                                            st.dtype, blk, imap))
            captured.append(KernelModel(
                name="", kernel_name=getattr(base, "__name__", "<kernel>"),
                grid=grid_t, inputs=inputs, outputs=outputs,
                scratch=scratch, call_file=call_file, call_line=call_line,
                body_file=body_file, body_tree=body,
                static_kwargs=static_kw, kernel_fn=base))
            outs = [jnp.zeros(st.shape, st.dtype) for st in out_structs]
            return outs[0] if single_out else outs

        return runner

    pl.pallas_call = fake
    try:
        jax.eval_shape(functools.partial(fn, **kwargs), *args)
    finally:
        pl.pallas_call = real
    return captured


# ---------------------------------------------------------------------------
# grid evaluation helpers
# ---------------------------------------------------------------------------


def _grid_points(grid: Tuple[int, ...]) -> List[Tuple[int, ...]]:
    """Concrete grid coordinates to evaluate index maps on: the full
    product when small, else per-axis boundary samples (first, second,
    middle, last-1, last) crossed — index maps are affine in practice,
    so boundaries decide coverage and axis dependence."""
    if not grid:
        return [()]
    total = 1
    for g in grid:
        total *= max(g, 1)
    if total <= _GRID_CAP:
        pts = [()]
        for g in grid:
            pts = [p + (i,) for p in pts for i in range(max(g, 1))]
        return pts
    axes = []
    for g in grid:
        g = max(g, 1)
        axes.append(sorted({0, 1 if g > 1 else 0, g // 2,
                            g - 2 if g > 1 else 0, g - 1}))
    pts = [()]
    for ax in axes:
        pts = [p + (i,) for p in pts for i in ax]
    return pts


def _eval_map(imap, point):
    try:
        out = imap(*point)
    except Exception:                  # noqa: BLE001 — non-arithmetic map
        return None
    if not isinstance(out, tuple):
        out = (out,)
    try:
        return tuple(int(v) for v in out)
    except Exception:                  # noqa: BLE001 — traced values
        return None


def _axis_dependence(imap, grid) -> Optional[List[bool]]:
    """depends[a] = varying grid axis a changes the block index."""
    base = tuple(0 for _ in grid)
    ref = _eval_map(imap, base)
    if ref is None:
        return None
    depends = []
    for a, g in enumerate(grid):
        dep = False
        for probe in {1 if g > 1 else 0, g - 1}:
            if probe == 0:
                continue
            pt = tuple(probe if i == a else 0
                       for i in range(len(grid)))
            got = _eval_map(imap, pt)
            if got is None:
                return None
            if got != ref:
                dep = True
        depends.append(dep)
    return depends


# ---------------------------------------------------------------------------
# body AST helpers
# ---------------------------------------------------------------------------


def _calls_named(node: ast.AST, names) -> List[ast.Call]:
    return [n for n in ast.walk(node)
            if isinstance(n, ast.Call) and _last_name(n.func) in names]


def _body_has_iota_compare(body: Optional[ast.AST]) -> bool:
    """Does this function body compare anything iota-derived?  Coarse:
    any Compare whose subtree mentions an iota call or an iota-assigned
    name counts as 'masks its tail'."""
    if body is None:
        return False
    iota_names = set()
    for n in ast.walk(body):
        if isinstance(n, ast.Assign) and _calls_named(n.value, _IOTA_NAMES):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    iota_names.add(t.id)
    for n in ast.walk(body):
        if not isinstance(n, ast.Compare):
            continue
        for side in [n.left] + list(n.comparators):
            if _calls_named(side, _IOTA_NAMES):
                return True
            if any(isinstance(x, ast.Name) and x.id in iota_names
                   for x in ast.walk(side)):
                return True
    return False


def _has_tail_guard(model: "KernelModel") -> bool:
    """Tail-mask detection for PTA601: the kernel body itself, or any
    module-level helper it calls (one level — masking is routinely
    factored into ``_rebuild_p``-style helpers shared across kernels)."""
    body = model.body_tree
    if _body_has_iota_compare(body):
        return True
    fn = model.kernel_fn
    if body is None or fn is None:
        return False
    helpers = {_last_name(n.func) for n in ast.walk(body)
               if isinstance(n, ast.Call)}
    modglobals = getattr(fn, "__globals__", {})
    for name in helpers:
        h = modglobals.get(name) if name else None
        if not callable(h) or isinstance(h, type):
            continue
        _, hbody = _kernel_body(h)
        if _body_has_iota_compare(hbody):
            return True
    return False


class _KernelTaint(_Taint):
    """PTA2xx taint re-scoped to a kernel body: refs (the positional
    params) and ``program_id`` results are the taint sources; static
    kwargs bound via functools.partial stay clean."""

    def __call__(self, node):
        if isinstance(node, ast.Call) and \
                _last_name(node.func) in _PID_NAMES:
            return True
        return super().__call__(node)


# ---------------------------------------------------------------------------
# the passes
# ---------------------------------------------------------------------------


class _Ctx:
    def __init__(self, report: Report, model: KernelModel):
        self.report = report
        self.model = model
        self._sups: Dict[str, Any] = {}
        self._spans: Dict[Tuple[str, int], Tuple[int, int]] = {}

    # -- suppression ------------------------------------------------------

    def _sup_for(self, path: Optional[str]):
        if not path:
            return None
        if path not in self._sups:
            try:
                with open(path, encoding="utf-8") as f:
                    self._sups[path] = parse_suppressions(f.read())
            except OSError:
                self._sups[path] = None
        return self._sups[path]

    def _call_span(self) -> Tuple[Optional[int], Optional[int]]:
        """Line span of the ``pallas_call(...)`` expression enclosing the
        recorded call line — the 'call header' a pragma may ride."""
        m = self.model
        key = (m.call_file or "", m.call_line or 0)
        if key in self._spans:
            return self._spans[key]
        span = (m.call_line, m.call_line)
        try:
            with open(m.call_file, encoding="utf-8") as f:
                tree = ast.parse(f.read())
            best = None
            for n in ast.walk(tree):
                if not (isinstance(n, ast.Call)
                        and _last_name(n.func) == "pallas_call"):
                    continue
                lo, hi = n.lineno, n.end_lineno or n.lineno
                if lo <= m.call_line <= hi and \
                        (best is None or (hi - lo) < (best[1] - best[0])):
                    best = (lo, hi)
            if best is not None:
                span = best
        except Exception:              # noqa: BLE001 — span is best-effort
            pass
        self._spans[key] = span
        return span

    def emit_call(self, rule: str, message: str, severity: Severity,
                  hint: Optional[str] = None):
        sup = self._sup_for(self.model.call_file)
        if sup is not None:
            lo, hi = self._call_span()
            if lo is not None and not all(
                    sup.allows(rule, ln) for ln in range(lo, hi + 1)):
                return
        self.report.add(Diagnostic(
            rule, message, severity, file=self.model.call_file,
            line=self.model.call_line, hint=hint))

    def emit_body(self, rule: str, node: ast.AST, message: str,
                  severity: Severity, hint: Optional[str] = None):
        line = getattr(node, "lineno", None)
        sup = self._sup_for(self.model.body_file)
        if sup is not None and not sup.allows(rule, line):
            return
        self.report.add(Diagnostic(
            rule, message, severity, file=self.model.body_file,
            line=line, hint=hint))


def _pass_tail_coverage(ctx: _Ctx):
    """PTA601: grid coverage vs operand dims, tail masks vs overruns."""
    m = ctx.model
    guarded = _has_tail_guard(m)
    pts = _grid_points(m.grid)
    for op in m.operands:
        if op.block_shape is None or op.index_map is None:
            continue
        idxs = [v for v in (_eval_map(op.index_map, p) for p in pts)
                if v is not None]
        if not idxs or len(idxs[0]) != len(op.block_shape):
            continue
        for d, blk in enumerate(op.block_shape):
            dim = op.shape[d]
            if blk <= 0:
                continue
            covered = (max(i[d] for i in idxs) + 1) * blk
            label = f"{m.name}.{op.label}"
            if op.kind == "out" and covered < dim:
                ctx.emit_call(
                    "PTA601",
                    f"{label}: grid covers only {covered} of {dim} "
                    f"rows along dim {d} (block {blk}, max block index "
                    f"{covered // blk - 1}) — the tail is never "
                    f"written and reads back as garbage",
                    Severity.ERROR,
                    hint="size the grid with pl.cdiv(dim, block) and "
                         "mask the tail block, or pad the operand to a "
                         "block multiple")
            elif op.kind == "in" and covered > dim and not guarded:
                ctx.emit_call(
                    "PTA601",
                    f"{label}: block {blk} does not divide dim {d} "
                    f"({dim}) and no iota tail mask guards the load — "
                    f"the overrun block reads garbage",
                    Severity.ERROR,
                    hint="mask with origin + broadcasted_iota < length "
                         "before reducing, or pad the operand")


def _pass_output_race(ctx: _Ctx):
    """PTA603: write-revisit order and injectivity of output maps."""
    m = ctx.model
    pts = _grid_points(m.grid)
    for op in m.outputs:
        if op.index_map is None:
            continue
        depends = _axis_dependence(op.index_map, m.grid)
        if depends is None:
            continue
        ignored = [a for a, (dep, g) in enumerate(zip(depends, m.grid))
                   if not dep and g > 1]
        used = [a for a, dep in enumerate(depends) if dep]
        label = f"{m.name}.{op.label}"
        if ignored and used and max(used) > min(ignored):
            ctx.emit_call(
                "PTA603",
                f"{label}: output index_map ignores grid axis "
                f"{min(ignored)} (size {m.grid[min(ignored)]}) while "
                f"axis {max(used)} varies inside it — revisits of one "
                f"output block interleave with other blocks, so two "
                f"grid points race on one write (last writer wins)",
                Severity.ERROR,
                hint="make reduced axes the innermost grid axes (then "
                     "accumulate in scratch and write on the last "
                     "visit), or include the axis in the index_map")
            continue
        seen: Dict[Tuple[int, ...], Tuple[int, ...]] = {}
        for p in pts:
            proj = tuple(p[a] for a in used)
            out = _eval_map(op.index_map, p)
            if out is None:
                break
            if proj in seen:
                continue
            if out in seen.values():
                ctx.emit_call(
                    "PTA603",
                    f"{label}: output index_map is not injective — "
                    f"grid points with distinct coordinates on its "
                    f"used axes map onto block {out}, two grid points "
                    f"write one block",
                    Severity.ERROR,
                    hint="an output block must have exactly one "
                         "producing grid point per sweep of the "
                         "reduced axes")
                break
            seen[proj] = out


def _pass_low_precision(ctx: _Ctx):
    """PTA602: dots without an f32 accumulator; += into half refs."""
    m = ctx.model
    body = m.body_tree
    if body is None:
        return
    half = {"bfloat16", "float16"}
    halfprec = any(np.dtype(op.dtype).name in ("float16",)
                   or str(op.dtype) in half for op in m.operands)
    # name -> dtype for resolvable (named-param) kernels
    names = _param_names(body)
    dtypes: Dict[str, Any] = {}
    if names:
        slots = [op.dtype for op in m.operands] + \
            [dt for _, dt in m.scratch]
        for n, dt in zip(names, slots):
            dtypes[n] = dt
    for node in ast.walk(body):
        if isinstance(node, ast.Call) and \
                _last_name(node.func) in _DOT_NAMES:
            fname = _last_name(node.func) or ""
            if any(h in fname for h in _SAFE_DOT_HELPERS):
                continue
            kws = {k.arg for k in node.keywords}
            if "preferred_element_type" not in kws and halfprec:
                ctx.emit_body(
                    "PTA602", node,
                    f"{m.name}: `{fname}` on a kernel with bf16/f16 "
                    f"operands and no preferred_element_type — the "
                    f"product accumulates at input precision",
                    Severity.WARNING,
                    hint="pass preferred_element_type=jnp.float32 (or "
                         "use ops.pallas.common.dot_nt)")
        elif isinstance(node, ast.BinOp) and \
                isinstance(node.op, ast.MatMult) and halfprec:
            ctx.emit_body(
                "PTA602", node,
                f"{m.name}: `@` matmul in a kernel with bf16/f16 "
                f"operands accumulates at input precision",
                Severity.WARNING,
                hint="use jax.lax.dot_general with "
                     "preferred_element_type=jnp.float32")
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Subscript) and \
                isinstance(node.target.value, ast.Name):
            dt = dtypes.get(node.target.value.id)
            if dt is not None and str(dt) in half:
                ctx.emit_body(
                    "PTA602", node,
                    f"{m.name}: `+=` carry into half-precision ref "
                    f"`{node.target.value.id}` — repeated adds round "
                    f"to nothing",
                    Severity.WARNING,
                    hint="accumulate in an f32 VMEM scratch and cast "
                         "once on the final write")


def _pass_tail_origin(ctx: _Ctx):
    """PTA604: iota compared against a length without the block origin."""
    m = ctx.model
    body = m.body_tree
    if body is None or not any(g > 1 for g in m.grid):
        return
    taint = _KernelTaint(set())        # pid taint via _KernelTaint.Call
    pid_names, iota_unanchored = set(), set()
    for n in ast.walk(body):
        if not isinstance(n, ast.Assign):
            continue
        anchored = bool(_calls_named(n.value, _PID_NAMES)) or any(
            isinstance(x, ast.Name) and x.id in pid_names
            for x in ast.walk(n.value))
        if anchored:
            for t in n.targets:
                if isinstance(t, ast.Name):
                    pid_names.add(t.id)
                    iota_unanchored.discard(t.id)
            continue
        if _calls_named(n.value, _IOTA_NAMES) or any(
                isinstance(x, ast.Name) and x.id in iota_unanchored
                for x in ast.walk(n.value)):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    iota_unanchored.add(t.id)
    del taint

    def _unanchored_iota(side) -> bool:
        has_iota = bool(_calls_named(side, _IOTA_NAMES)) or any(
            isinstance(x, ast.Name) and x.id in iota_unanchored
            for x in ast.walk(side))
        if not has_iota:
            return False
        anchored = bool(_calls_named(side, _PID_NAMES)) or any(
            isinstance(x, ast.Name) and x.id in pid_names
            for x in ast.walk(side))
        return not anchored

    for n in ast.walk(body):
        if not isinstance(n, ast.Compare):
            continue
        for side in [n.left] + list(n.comparators):
            if _unanchored_iota(side):
                ctx.emit_body(
                    "PTA604", n,
                    f"{m.name}: iota compared against a length without "
                    f"a program_id-derived block origin while the grid "
                    f"has multiple blocks — every block but the first "
                    f"is mis-masked",
                    Severity.ERROR,
                    hint="compare `axis_block_index * block + iota` "
                         "against the length, not the bare iota")
                break


def _pass_vmem(ctx: _Ctx, budget_kb: int):
    """PTA605: 2×(in+out blocks) + scratch vs the VMEM budget flag."""
    m = ctx.model
    blocks = sum(op.block_bytes() for op in m.operands) * 2
    scratch = sum(int(np.prod(s, dtype=np.int64))
                  * np.dtype(dt).itemsize for s, dt in m.scratch)
    total = blocks + scratch
    if budget_kb > 0 and total > budget_kb * 1024:
        ctx.emit_call(
            "PTA605",
            f"{m.name}: analytic VMEM footprint {total // 1024} KB "
            f"(2× double-buffered blocks {blocks // 1024} KB + scratch "
            f"{scratch // 1024} KB) exceeds the "
            f"{budget_kb} KB budget (FLAGS_pallas_vmem_budget_kb)",
            Severity.WARNING,
            hint="shrink block shapes or scratch; raise the flag only "
                 "if the target core really has the headroom")


def _pass_static_flow(ctx: _Ctx):
    """PTA606: Python control flow on ref-/program_id-derived values."""
    m = ctx.model
    body = m.body_tree
    if body is None:
        return
    tainted = set()
    a = body.args
    for p in list(getattr(a, "posonlyargs", [])) + list(a.args):
        tainted.add(p.arg)             # positional params are refs
    if a.vararg is not None:
        tainted.add(a.vararg.arg)
    taint = _KernelTaint(tainted)

    def walk(stmts):
        for st in stmts:
            if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = getattr(st, "value", None)
                if value is not None and taint(value):
                    targets = st.targets if isinstance(st, ast.Assign) \
                        else [st.target]
                    for t in targets:
                        for x in ast.walk(t):
                            if isinstance(x, ast.Name):
                                tainted.add(x.id)
            if isinstance(st, ast.If):
                if taint(st.test):
                    ctx.emit_body(
                        "PTA606", st,
                        f"{m.name}: Python `if` on a ref-/program_id-"
                        f"derived value inside the kernel body — the "
                        f"trace concretizes (or crashes) on a tracer",
                        Severity.ERROR,
                        hint="use pl.when(...) or jnp.where; Python "
                             "branches may only test static kwargs")
                walk(st.body)
                walk(st.orelse)
            elif isinstance(st, ast.While):
                if taint(st.test):
                    ctx.emit_body(
                        "PTA606", st,
                        f"{m.name}: Python `while` bounded by a traced "
                        f"kernel value",
                        Severity.ERROR,
                        hint="use jax.lax control flow; kernel loops "
                             "must have static trip counts")
                walk(st.body)
            elif isinstance(st, ast.For):
                if taint(st.iter):
                    ctx.emit_body(
                        "PTA606", st,
                        f"{m.name}: Python `for` bounded by a traced "
                        f"kernel value (e.g. range over a ref load)",
                        Severity.ERROR,
                        hint="loop bounds inside a kernel must be "
                             "static (grid axes or static kwargs)")
                walk(st.body)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(st.body)          # pl.when callees are kernel code
            elif isinstance(st, ast.With):
                walk(st.body)
            elif isinstance(st, ast.Try):
                walk(st.body)
                for h in st.handlers:
                    walk(h.body)
                walk(st.finalbody)

    walk(body.body)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def analyze_kernels(fn, *args, name: str = "kernels",
                    disable: Sequence[str] = (),
                    vmem_budget_kb: Optional[int] = None,
                    **kwargs) -> Report:
    """Trace ``fn(*args)``, extract a kernel model per ``pallas_call``,
    run the PTA6xx passes, return a :class:`Report`.

    ``name`` prefixes every operand label (``<name>.<operand>``) — use
    the same name when arming the runtime oracle so both halves of the
    plane speak about one operand with one string.  A builder that
    reaches no ``pallas_call`` yields an empty (clean) report — the
    passes are a no-op on plain XLA programs.
    """
    if vmem_budget_kb is None:
        try:
            from paddle_tpu.framework.flags import flag
            vmem_budget_kb = int(flag("pallas_vmem_budget_kb"))
        except Exception:              # noqa: BLE001 — analyzable without flags
            vmem_budget_kb = 16384
    models = trace_kernels(fn, *args, **kwargs)
    report = Report()
    for i, m in enumerate(models):
        m.name = name if len(models) == 1 else \
            f"{name}.{m.kernel_name.strip('_') or i}"
        ctx = _Ctx(report, m)
        _pass_tail_coverage(ctx)
        _pass_output_race(ctx)
        _pass_low_precision(ctx)
        _pass_tail_origin(ctx)
        _pass_vmem(ctx, vmem_budget_kb)
        _pass_static_flow(ctx)
    if disable:
        report = report.filter(disable=disable)
    return report
