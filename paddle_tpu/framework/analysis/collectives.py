"""Distributed-semantics analysis passes (PTA5xx) — the fourth front end
on the shared Diagnostic core.

The repo now carries five distinct sharded-execution paths
(``parallel/zero.py``, ``parallel/sharded.py``, ``parallel/dp_meta.py``,
``parallel/ring_attention.py``, the PS pipeline) whose correctness
contracts — every gradient reduced exactly once on ``dp``, replicas
bit-identical after the update, quantized payloads never summed by a
collective — were enforced only by example-specific tests.  These passes
make the contracts whole-program facts: they walk ``shard_map``/``pjit``
regions of a traced jaxpr and re-run the replication analysis the repo
deliberately disables at trace time (every manual region goes through
``mesh.shard_map_compat`` with ``check_vma/check_rep=False``) as
*diagnostics* instead of trace errors.

The core is a mapped-axis **varying set** per value (the vma/check_rep
lattice): a value is *varying* over a mesh axis when replicas along that
axis may hold different data.  Sources: inputs whose ``in_names`` shard
a dim over the axis, and ``axis_index``.  Sinks: ``psum``/``pmax``/
``pmin`` and ``all_gather`` (no ``axis_index_groups``) clear the axis;
``psum_scatter``/``all_to_all``/``ppermute`` keep it (replicas still
hold different chunks).  Everything else unions its operands.

Shipped passes (stable IDs, see diagnostics.RULES):

========  ==============================================================
PTA501    unreduced value on a mapped axis: a shard_map output whose
          ``out_names`` claim replication over an axis the value still
          varies on — the grad-leaf-reaches-the-optimizer-without-a-
          psum bug; replicas silently diverge (error).  A *complete
          ring* scan is recognized as a gather: a scan whose body
          ``ppermute``s over axis A with a single full cycle of size
          ``n = |A|`` and runs ``n`` or ``n-1`` trips has shown every
          replica every chunk, so scan outputs with leading dim ``n``
          (the assembled buffer) stop varying over A
          (``parallel/ring.py``'s ring_all_gather)
PTA502    collective axis mismatch: an axis name absent from the
          enclosing manual region (error), or a ``psum`` of an
          already-replicated value that is not a ``pmean`` — the
          double reduction multiplies by the axis size (warning)
PTA503    replicated/sharded mixing: ``all_gather`` whose only
          consumers statically slice one chunk back out — every
          replica gets chunk 0; a ``dynamic_slice`` at
          ``axis_index * shard_len`` was almost certainly meant
PTA504    quantized payload summed by a collective: int8 rows fed to
          ``psum``/``psum_scatter`` (error — the sum of encodings is
          not the encoding of the sum) or bf16/f16 payloads (warning —
          the wire accumulates in reduced precision); the legal idioms
          are ``wire.py`` quantize → ``all_to_all``/``all_gather`` →
          dequantize → local sum, and the fused ring
          (``parallel/ring.py``): quantize inside a ``ppermute`` scan
          carry with an **f32 accumulator**.  The pass also flags the
          fused ring gone wrong — an ``add`` consuming a ``ppermute``
          result that is still int8/uint8 encoded (error) or bf16/f16
          (warning) sums encoded payloads one hop at a time
PTA505    donated buffer crossing a collective boundary: a donated
          input consumed *directly* by a collective with no
          shape/dtype-matching output to alias — XLA cannot reuse the
          storage across the collective, so the donation only deletes
          the caller's array (warning)
PTA506    collective under a divergent traced conditional: a
          collective inside a ``cond``/``while`` region whose
          predicate varies over the collective's axis — replicas take
          different branches and the collective deadlocks on TPU
          (error); uniform predicates (the LocalSGD sync gate) pass
========  ==============================================================

Entry points: :func:`analyze_collectives` standalone, and
``jaxpr_passes.analyze_jaxpr`` runs the family over every trace — so
``TrainStep.analyze()`` / ``ShardedUpdateTrainStep.analyze()`` and the
``prog_lint --collectives`` zoo audit distributed semantics for free.
Jaxpr diagnostics carry no source line; suppress by rule ID via the
``disable=`` argument / ``--disable`` (the PTA1xx discipline).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.framework.analysis.diagnostics import (
    Diagnostic, Report, Severity, register_rule)

__all__ = ["analyze_collectives", "run_collective_passes",
           "COLLECTIVE_PRIMS"]

register_rule("PTA501", "unreduced value on a mapped axis",
              Severity.ERROR, "collective")
register_rule("PTA502", "collective axis mismatch / double reduction",
              Severity.ERROR, "collective")
register_rule("PTA503", "replicated/sharded mixing (gather-then-slice)",
              Severity.WARNING, "collective")
register_rule("PTA504", "quantized payload summed by a collective",
              Severity.ERROR, "collective")
register_rule("PTA505", "donated buffer crosses a collective boundary",
              Severity.WARNING, "collective")
register_rule("PTA506", "collective under a divergent traced conditional",
              Severity.ERROR, "collective")

#: collectives that REDUCE over their axes (replicas agree afterwards)
_REDUCE_PRIMS = frozenset({"psum", "pmax", "pmin"})
#: collectives whose output is identical on every group member
_GATHER_PRIMS = frozenset({"all_gather"})
#: collectives whose output still differs per replica (chunks move)
_VARY_KEEP_PRIMS = frozenset({"psum_scatter", "reduce_scatter",
                              "all_to_all", "ppermute", "pbroadcast"})
#: collectives whose payload is SUMMED elementwise on the wire
_SUM_PRIMS = frozenset({"psum", "psum_scatter", "reduce_scatter"})

COLLECTIVE_PRIMS = _REDUCE_PRIMS | _GATHER_PRIMS | _VARY_KEEP_PRIMS

# eqn.params keys holding nested jaxprs for generic call-like descent
_CALL_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")

_EMPTY = frozenset()


def _collective_axes(eqn) -> Tuple[str, ...]:
    """Axis names of a collective eqn, across the per-primitive
    spellings (``axes`` for psum/pmax/pmin, ``axis_name`` for the
    rest; tuples may nest)."""
    ax = eqn.params.get("axes", eqn.params.get("axis_name"))
    if ax is None:
        return ()
    out: List[str] = []
    stack = [ax]
    while stack:
        a = stack.pop()
        if isinstance(a, (tuple, list, frozenset, set)):
            stack.extend(a)
        elif isinstance(a, str):
            out.append(a)
    return tuple(sorted(out))


def _names_axes(names) -> frozenset:
    """Axis set of one shard_map in_names/out_names entry
    (``{dim: (axes...)}`` → the union of all named axes)."""
    out = set()
    for axes in (names or {}).values():
        if isinstance(axes, (tuple, list)):
            out.update(a for a in axes if isinstance(a, str))
        elif isinstance(axes, str):
            out.add(axes)
    return frozenset(out)


def _np_dtype(aval):
    try:
        return np.dtype(getattr(aval, "dtype", None))
    except TypeError:
        return None


def _aval_key(aval):
    return (tuple(getattr(aval, "shape", ())), _np_dtype(aval))


class _Ctx:
    """Per-analysis state threaded through the walk."""

    __slots__ = ("report", "name", "manual", "sizes", "donated",
                 "out_labels", "out_avals", "seen_manual", "flagged_505",
                 "ppermute_outs", "flagged_ring_sum")

    def __init__(self, report: Report, name: str):
        self.report = report
        self.name = name
        self.manual: frozenset = _EMPTY     # manual axes in scope
        self.sizes: Dict[str, int] = {}     # mesh axis -> size
        # donated *body* vars -> (outer global-view aval key, label)
        self.donated: Dict[object, object] = {}
        self.out_labels: Dict[object, str] = {}   # program outvar -> label
        self.out_avals: List[tuple] = []    # program output (shape, dtype)
        self.seen_manual = False
        self.flagged_505: set = set()       # one finding per donated var
        # ppermute result vars -> dtype (the fused-ring PTA504 check)
        self.ppermute_outs: Dict[object, object] = {}
        self.flagged_ring_sum: set = set()  # one finding per add eqn


def _vary(env, v) -> frozenset:
    import jax
    if isinstance(v, jax.core.Literal):
        return _EMPTY
    return env.get(v, _EMPTY)


def _is_mean_psum(eqn, jaxpr, ctx: _Ctx) -> bool:
    """True when this psum's result is immediately divided by the
    product of its axis sizes — the ``pmean`` lowering, which is the
    identity on an already-replicated value (sum·k/k), not the
    multiply-by-k double reduction PTA502 warns about."""
    import jax
    axes = _collective_axes(eqn)
    k = 1
    for a in axes:
        k *= int(ctx.sizes.get(a, 0) or 0)
    if k <= 0:
        return False
    outs = set(eqn.outvars)
    for consumer in jaxpr.eqns:
        if consumer.primitive.name != "div":
            continue
        if consumer.invars and consumer.invars[0] in outs:
            d = consumer.invars[1]
            if not isinstance(d, jax.core.Literal):
                continue
            try:
                if float(np.asarray(d.val)) == float(k):
                    return True
            except (TypeError, ValueError):
                continue
    return False


def _check_gather_then_slice(eqn, jaxpr, ctx: _Ctx):
    """PTA503: every consumer of this all_gather statically slices a
    single pre-gather chunk back out — chunk 0 on every device."""
    import jax
    out = eqn.outvars[0]
    dim = int(eqn.params.get("all_gather_dimension", 0))
    size = int(eqn.params.get("axis_size", 0) or 0)
    if size <= 1:
        return
    tiled = bool(eqn.params.get("tiled", False))
    in_aval = getattr(eqn.invars[0], "aval", None)
    if in_aval is None or not getattr(in_aval, "shape", None):
        local = None
    else:
        local = in_aval.shape[dim] if dim < len(in_aval.shape) else None
    consumers = [e for e in jaxpr.eqns
                 if any((not isinstance(v, jax.core.Literal)) and v is out
                        for v in e.invars)]
    if not consumers:
        return

    def _is_chunk_slice(e):
        if e.primitive.name != "slice":
            return False
        starts = e.params.get("start_indices", ())
        limits = e.params.get("limit_indices", ())
        if dim >= len(starts):
            return False
        span = limits[dim] - starts[dim]
        if tiled:
            return local is not None and span == local
        return span == 1              # one gathered row of the new dim
    if all(_is_chunk_slice(e) for e in consumers):
        ctx.report.add(Diagnostic(
            "PTA503",
            f"{ctx.name}: all_gather result is only consumed by static "
            "slices of one chunk — every replica reads the SAME chunk, "
            "mixing a replicated gather with per-replica intent",
            Severity.WARNING,
            hint="dynamic_slice at axis_index(axis) * shard_len selects "
                 "each replica's own chunk without moving the other "
                 "replicas' data at all"))


def _check_collective(eqn, jaxpr, env, ctx: _Ctx, pred_vary: frozenset):
    import jax
    pname = eqn.primitive.name
    axes = _collective_axes(eqn)
    groups = eqn.params.get("axis_index_groups")
    unknown = [a for a in axes if a not in ctx.manual]
    if unknown:
        ctx.report.add(Diagnostic(
            "PTA502",
            f"{ctx.name}: collective `{pname}` names axis "
            f"{unknown if len(unknown) > 1 else unknown[0]!r} which is "
            "not a manual axis of the enclosing shard_map region "
            f"(manual: {sorted(ctx.manual) or 'none'})",
            Severity.ERROR,
            hint="add the axis to the mesh/manual set, or move the "
                 "collective inside the shard_map that binds it"))
    hot = pred_vary & set(axes)
    if hot:
        ctx.report.add(Diagnostic(
            "PTA506",
            f"{ctx.name}: collective `{pname}` over {sorted(hot)} inside "
            "a traced conditional whose predicate varies over the same "
            "axis — replicas that take different branches deadlock the "
            "collective on TPU",
            Severity.ERROR,
            hint="hoist the collective out of the cond/while, or make "
                 "the predicate replicated (psum/pmean it) first"))
    if pname in _SUM_PRIMS:
        for v in eqn.invars:
            dt = _np_dtype(getattr(v, "aval", None))
            if dt is None:
                continue
            if dt in (np.dtype(np.int8), np.dtype(np.uint8)):
                ctx.report.add(Diagnostic(
                    "PTA504",
                    f"{ctx.name}: `{pname}` sums an {dt}-encoded "
                    "payload — the sum of quantized encodings is not "
                    "the encoding of the sum (garbage after one hop)",
                    Severity.ERROR,
                    hint="use the wire.py idiom: quantize -> "
                         "all_to_all/all_gather -> dequantize -> local "
                         "sum (parallel/zero.py reduce_scatter leg)"))
            elif dt.name in ("bfloat16", "float16"):
                ctx.report.add(Diagnostic(
                    "PTA504",
                    f"{ctx.name}: `{pname}` reduces a {dt} payload — "
                    "the wire accumulates in half precision, so the "
                    "reduced value loses bits the operands still had",
                    Severity.WARNING,
                    hint="exchange the encoded rows (all_to_all/"
                         "all_gather) and sum after dequantizing to "
                         "f32, or reduce in f32 and cast afterwards"))
    if pname == "psum" and axes and groups is None:
        for v in eqn.invars:
            if isinstance(v, jax.core.Literal):
                continue
            if _vary(env, v).isdisjoint(axes) and \
                    not _is_mean_psum(eqn, jaxpr, ctx):
                ctx.report.add(Diagnostic(
                    "PTA502",
                    f"{ctx.name}: psum over {list(axes)} of a value "
                    "already replicated on those axes — the second "
                    "reduction multiplies by the axis size",
                    Severity.WARNING,
                    hint="drop the redundant psum (or use pmean if the "
                         "multiply-by-world-size was the bug)"))
                break
    if pname in _GATHER_PRIMS:
        _check_gather_then_slice(eqn, jaxpr, ctx)
    for v in eqn.invars:
        if v in ctx.donated and v not in ctx.flagged_505:
            key, label = ctx.donated[v]
            if key in ctx.out_avals:
                continue              # round-trips to an aliasable output
            ctx.flagged_505.add(v)
            shape, dt = key
            ctx.report.add(Diagnostic(
                "PTA505",
                f"{ctx.name}: donated input `{label}` "
                f"({dt}{list(shape)}) is consumed directly by "
                f"`{pname}` and no output matches its shape/dtype — "
                "XLA cannot reuse donated storage across a collective "
                "boundary, so the donation only deletes the caller's "
                "array",
                Severity.WARNING,
                hint="drop it from donate_argnums, or return an "
                     "updated buffer of the same shape so the alias "
                     "survives"))


def _check_ring_sum(eqn, ctx: _Ctx):
    """PTA504, fused-ring flavor: an ``add`` consuming a ``ppermute``
    result that is still wire-encoded.  The legal hop body decodes the
    received chunk to f32 first (``parallel/ring.py``); adding raw
    encodings accumulates garbage (int8) or half-precision error
    (bf16/f16) on every hop."""
    import jax
    if id(eqn) in ctx.flagged_ring_sum:
        return                        # scan fixpoint re-walks the body
    for v in eqn.invars:
        if isinstance(v, jax.core.Literal) or v not in ctx.ppermute_outs:
            continue
        dt = ctx.ppermute_outs[v]
        if dt in (np.dtype(np.int8), np.dtype(np.uint8)):
            ctx.flagged_ring_sum.add(id(eqn))
            ctx.report.add(Diagnostic(
                "PTA504",
                f"{ctx.name}: fused ring sums encoded payloads — "
                f"`add` consumes a {dt} `ppermute` result directly, "
                "so each hop accumulates quantized encodings instead "
                "of values (garbage after one hop)",
                Severity.ERROR,
                hint="dequantize the received chunk to f32, add the "
                     "local block at full precision, and re-encode "
                     "for the next hop (parallel/ring.py hop body)"))
            return
        if dt is not None and dt.name in ("bfloat16", "float16"):
            ctx.flagged_ring_sum.add(id(eqn))
            ctx.report.add(Diagnostic(
                "PTA504",
                f"{ctx.name}: fused ring accumulates in {dt} — `add` "
                "consumes a ppermute result without widening, so the "
                "partial sum loses bits on every hop",
                Severity.WARNING,
                hint="accumulate the ring carry in f32 and cast back "
                     "to the wire dtype only for the next ppermute"))
            return


def _is_full_cycle(perm, n: int) -> bool:
    """True iff ``perm`` is a permutation of ``range(n)`` forming one
    cycle that visits every member — the neighbor rotation every ring
    hop reuses."""
    try:
        step = {int(s): int(d) for s, d in (perm or ())}
    except (TypeError, ValueError):
        return False
    if len(step) != n or set(step) != set(range(n)) \
            or set(step.values()) != set(range(n)):
        return False
    cur = 0
    for hops in range(1, n + 1):
        cur = step[cur]
        if cur == 0:
            return hops == n
    return False


def _scan_ring_axes(eqn, body, ctx: _Ctx) -> frozenset:
    """Axes over which this scan is a *complete ring*: the body
    ``ppermute``s over axis A with a single full cycle of size
    ``n = |A|`` and the scan runs ``n`` or ``n-1`` trips — by the last
    trip every replica has seen every replica's chunk, so an assembled
    buffer (leading dim ``n``) no longer varies over A."""
    length = eqn.params.get("length")
    if length is None or not hasattr(body, "eqns"):
        return _EMPTY
    out = set()
    for beqn in body.eqns:
        if beqn.primitive.name != "ppermute":
            continue
        for a in _collective_axes(beqn):
            n = int(ctx.sizes.get(a, 0) or 0)
            if n >= 2 and int(length) in (n, n - 1) \
                    and _is_full_cycle(beqn.params.get("perm"), n):
                out.add(a)
    return frozenset(out)


def _call_body(eqn):
    for k in _CALL_KEYS:
        v = eqn.params.get(k)
        if v is not None:
            return getattr(v, "jaxpr", v)
    return None


def _bind(env, ctx, outer_vars, inner_vars):
    """Map call-like eqn invars onto body invars.  Aligned from the END
    when lengths differ (leading const conventions); unmatched body
    invars conservatively inherit the union of every operand."""
    import jax
    n_in, n_body = len(outer_vars), len(inner_vars)
    union = _EMPTY
    for v in outer_vars:
        union |= _vary(env, v)
    off = n_body - n_in
    for j, bv in enumerate(inner_vars):
        i = j - off
        if 0 <= i < n_in:
            ov = outer_vars[i]
            env[bv] = _vary(env, ov)
            if not isinstance(ov, jax.core.Literal) and ov in ctx.donated:
                ctx.donated[bv] = ctx.donated[ov]
        else:
            env[bv] = union


def _walk(jaxpr, env, ctx: _Ctx, pred_vary: frozenset):
    """One pass over ``jaxpr``'s eqns, propagating varying sets and
    emitting diagnostics.  Recurses into every nested region."""
    import jax
    for eqn in jaxpr.eqns:
        pname = eqn.primitive.name
        union = _EMPTY
        for v in eqn.invars:
            union |= _vary(env, v)
        if pname in COLLECTIVE_PRIMS:
            _check_collective(eqn, jaxpr, env, ctx, pred_vary)
            axes = frozenset(_collective_axes(eqn))
            if eqn.params.get("axis_index_groups") is not None:
                out = union               # group reduces stay conservative
            elif pname in _REDUCE_PRIMS or pname in _GATHER_PRIMS:
                out = union - axes
            else:
                out = union
            for o in eqn.outvars:
                env[o] = out
                if pname == "ppermute":
                    ctx.ppermute_outs[o] = _np_dtype(
                        getattr(o, "aval", None))
            continue
        if pname in ("add", "add_any"):
            _check_ring_sum(eqn, ctx)
        if pname == "axis_index":
            ax = eqn.params.get("axis_name")
            axset = frozenset(a for a in (
                ax if isinstance(ax, (tuple, list)) else (ax,))
                if isinstance(a, str))
            for o in eqn.outvars:
                env[o] = axset
            continue
        if pname == "shard_map":
            _walk_shard_map(eqn, env, ctx)
            continue
        if pname == "cond":
            _walk_cond(eqn, env, ctx, pred_vary)
            continue
        if pname == "while":
            _walk_while(eqn, env, ctx, pred_vary)
            continue
        if pname == "scan":
            _walk_scan(eqn, env, ctx, pred_vary)
            continue
        body = _call_body(eqn)
        if body is not None:
            _bind(env, ctx, list(eqn.invars), list(body.invars))
            _walk(body, env, ctx, pred_vary)
            bouts = list(body.outvars)
            for i, o in enumerate(eqn.outvars):
                env[o] = _vary(env, bouts[i]) if i < len(bouts) else union
            continue
        for o in eqn.outvars:
            env[o] = union


def _walk_shard_map(eqn, env, ctx: _Ctx):
    import jax
    p = eqn.params
    mesh = p.get("mesh")
    axis_names = tuple(getattr(mesh, "axis_names", ()) or ())
    auto = p.get("auto") or frozenset()
    manual = frozenset(a for a in axis_names if a not in auto)
    body = getattr(p.get("jaxpr"), "jaxpr", p.get("jaxpr"))
    if body is None or not hasattr(body, "eqns"):
        return
    in_names = p.get("in_names") or ()
    out_names = p.get("out_names") or ()
    for i, bv in enumerate(body.invars):
        names = in_names[i] if i < len(in_names) else {}
        env[bv] = _names_axes(names) & manual
        ov = eqn.invars[i] if i < len(eqn.invars) else None
        if ov is not None and not isinstance(ov, jax.core.Literal) \
                and ov in ctx.donated:
            ctx.donated[bv] = ctx.donated[ov]
    saved = (ctx.manual, ctx.sizes, ctx.seen_manual)
    ctx.manual = manual
    try:
        shp = dict(getattr(mesh, "shape", {}) or {})
    except TypeError:
        shp = {}
    ctx.sizes = {a: int(s) for a, s in shp.items()}
    ctx.seen_manual = True
    try:
        _walk(body, env, ctx, _EMPTY)
        for j, bov in enumerate(body.outvars):
            claimed = _names_axes(out_names[j] if j < len(out_names)
                                  else {})
            leak = _vary(env, bov) - claimed
            if leak:
                outer = eqn.outvars[j] if j < len(eqn.outvars) else None
                label = ctx.out_labels.get(outer, f"output[{j}]")
                ctx.report.add(Diagnostic(
                    "PTA501",
                    f"{ctx.name}: shard_map output `{label}` is claimed "
                    f"replicated over {sorted(leak)} but still varies "
                    "there — no psum/psum_scatter/all_gather reduced it, "
                    "so replicas silently diverge (each applies its own "
                    "local value)",
                    Severity.ERROR,
                    hint="psum (grads), pmean (buffers/loss) or "
                         "all_gather (updated shards) the value on "
                         f"{sorted(leak)}, or declare the output sharded "
                         "over that axis in out_specs"))
    finally:
        ctx.manual, ctx.sizes, ctx.seen_manual = saved
    for o in eqn.outvars:
        env[o] = _EMPTY               # global view outside the region


def _walk_cond(eqn, env, ctx: _Ctx, pred_vary: frozenset):
    pred = eqn.invars[0]
    ops = list(eqn.invars[1:])
    inner_pred = pred_vary | _vary(env, pred)
    branches = eqn.params.get("branches") or ()
    out_sets = [_EMPTY] * len(eqn.outvars)
    for br in branches:
        body = getattr(br, "jaxpr", br)
        _bind(env, ctx, ops, list(body.invars))
        _walk(body, env, ctx, inner_pred)
        for i in range(len(eqn.outvars)):
            if i < len(body.outvars):
                out_sets[i] = out_sets[i] | _vary(env, body.outvars[i])
    for i, o in enumerate(eqn.outvars):
        env[o] = out_sets[i] | _vary(env, pred)


def _walk_while(eqn, env, ctx: _Ctx, pred_vary: frozenset):
    p = eqn.params
    cond_j = getattr(p.get("cond_jaxpr"), "jaxpr", p.get("cond_jaxpr"))
    body_j = getattr(p.get("body_jaxpr"), "jaxpr", p.get("body_jaxpr"))
    cn = int(p.get("cond_nconsts", 0))
    bn = int(p.get("body_nconsts", 0))
    cond_consts = list(eqn.invars[:cn])
    body_consts = list(eqn.invars[cn:cn + bn])
    carry = list(eqn.invars[cn + bn:])
    carry_vary = [_vary(env, v) for v in carry]
    inner_pred = pred_vary
    for _ in range(8):                   # fixpoint over the carry lattice
        if cond_j is not None:
            _bind(env, ctx, cond_consts + carry, list(cond_j.invars))
            for i, bv in enumerate(cond_j.invars[len(cond_consts):]):
                env[bv] = carry_vary[i] if i < len(carry_vary) else _EMPTY
            _walk(cond_j, env, ctx, inner_pred)
            pv = _EMPTY
            for ov in cond_j.outvars:
                pv |= _vary(env, ov)
            inner_pred = pred_vary | pv
        if body_j is None:
            break
        _bind(env, ctx, body_consts + carry, list(body_j.invars))
        for i, bv in enumerate(body_j.invars[len(body_consts):]):
            env[bv] = carry_vary[i] if i < len(carry_vary) else _EMPTY
        _walk(body_j, env, ctx, inner_pred)
        new = [_vary(env, ov) if i < len(body_j.outvars) else _EMPTY
               for i, ov in enumerate(body_j.outvars)]
        new = [carry_vary[i] | (new[i] if i < len(new) else _EMPTY)
               for i in range(len(carry_vary))]
        if new == carry_vary:
            break
        carry_vary = new
    for i, o in enumerate(eqn.outvars):
        env[o] = (carry_vary[i] if i < len(carry_vary) else _EMPTY) \
            | inner_pred


def _walk_scan(eqn, env, ctx: _Ctx, pred_vary: frozenset):
    p = eqn.params
    body = getattr(p.get("jaxpr"), "jaxpr", p.get("jaxpr"))
    if body is None:
        return
    nc = int(p.get("num_consts", 0))
    ncar = int(p.get("num_carry", 0))
    consts = list(eqn.invars[:nc])
    carry = list(eqn.invars[nc:nc + ncar])
    xs = list(eqn.invars[nc + ncar:])
    carry_vary = [_vary(env, v) for v in carry]
    for _ in range(8):                   # fixpoint: trip-uniform schedule
        _bind(env, ctx, consts + carry + xs, list(body.invars))
        for i in range(ncar):
            j = nc + i
            if j < len(body.invars):
                env[body.invars[j]] = carry_vary[i]
        _walk(body, env, ctx, pred_vary)
        new = [_vary(env, body.outvars[i]) if i < len(body.outvars)
               else _EMPTY for i in range(ncar)]
        new = [carry_vary[i] | new[i] for i in range(ncar)]
        if new == carry_vary:
            break
        carry_vary = new
    for i, o in enumerate(eqn.outvars):
        if i < ncar:
            env[o] = carry_vary[i]
        else:
            j = i
            env[o] = _vary(env, body.outvars[j]) \
                if j < len(body.outvars) else _EMPTY
    ring_axes = _scan_ring_axes(eqn, body, ctx)
    if ring_axes:
        # complete-ring gather: outputs holding one slot per replica
        # (leading dim == axis size) have been filled from every seat
        for o in eqn.outvars:
            shape = tuple(getattr(getattr(o, "aval", None),
                                  "shape", ()) or ())
            if not shape:
                continue
            done = frozenset(a for a in ring_axes
                             if int(ctx.sizes.get(a, 0)) == shape[0])
            if done:
                env[o] = _vary(env, o) - done


def run_collective_passes(closed_jaxpr, name: str, report: Report,
                          donate_argnums: Optional[Sequence[int]] = None,
                          invar_labels: Optional[Sequence[str]] = None,
                          outvar_labels: Optional[Sequence[str]] = None):
    """Run the PTA5xx family over a ``jax.make_jaxpr`` result, appending
    findings to ``report``.  A program with no shard_map region and no
    collective eqns produces no diagnostics — the passes are free for
    ordinary jit programs, which is what lets ``analyze_jaxpr`` run them
    unconditionally."""
    import jax
    jaxpr = closed_jaxpr.jaxpr
    ctx = _Ctx(report, name)
    if donate_argnums:
        for i in donate_argnums:
            if i < len(jaxpr.invars):
                v = jaxpr.invars[i]
                label = invar_labels[i] if invar_labels and \
                    i < len(invar_labels) else f"input[{i}]"
                ctx.donated[v] = (_aval_key(getattr(v, "aval", None)),
                                  label)
    ctx.out_avals = [_aval_key(getattr(o, "aval", None))
                     for o in jaxpr.outvars
                     if not isinstance(o, jax.core.Literal)]
    if outvar_labels:
        for o, lbl in zip(jaxpr.outvars, outvar_labels):
            if not isinstance(o, jax.core.Literal):
                ctx.out_labels[o] = lbl
    env: Dict[object, frozenset] = {}
    _walk(jaxpr, env, ctx, _EMPTY)
    return report


def analyze_collectives(closed_jaxpr, name: str = "<traced>",
                        donate_argnums: Optional[Sequence[int]] = None,
                        invar_labels: Optional[Sequence[str]] = None,
                        outvar_labels: Optional[Sequence[str]] = None,
                        disable: Sequence[str] = ()) -> Report:
    """Standalone entry: just the distributed-semantics passes over a
    traced program (the full stack lives in ``analyze_jaxpr``)."""
    report = Report()
    run_collective_passes(closed_jaxpr, name, report,
                          donate_argnums=donate_argnums,
                          invar_labels=invar_labels,
                          outvar_labels=outvar_labels)
    return report.filter(disable=disable)
