"""Concurrency pass family — the third front end on the Diagnostic core.

Where the jaxpr passes (PTA1xx) see what a trace produced and the
jit-safety lint (PTA2xx/3xx) sees what source will do to a trace, this
front end sees what the *threads* will do to each other.  It extracts a
whole-repo **lock model** from the AST — per-class and module lock
fields (``threading.Lock``/``RLock`` and the named
``framework.locks.lock``/``rlock`` wrappers), ``with lock:`` scopes,
explicit ``acquire``/``release`` pairs, queue/thread/executor fields,
thread spawn sites — propagates lock-acquisition summaries over a
resolvable call graph (``self.method``, module functions, imported
modules, module-level instances), and checks the result:

========  ==============================================================
PTA401    lock-order inversion: a cycle in the static acquisition
          graph (edge A→B = "B acquired while A held", direct nesting
          and through calls), including a self-deadlock on a
          non-reentrant lock
PTA402    blocking call under a held lock: ``socket.recv``/``accept``,
          ``subprocess``, ``Queue.get`` with no timeout, ``fsync``,
          thread/queue ``join`` — direct, or through a call whose
          callee blocks
PTA403    shared-mutable ``self`` attribute written from a ``Thread``
          target / executor task without a guarding lock, while other
          (non-thread) methods touch the same attribute
PTA404    check-then-act lazy init (``if x is None: x = ...``) on
          shared state outside any lock, in a class/module that owns
          locks — exempt when every same-class call site of the
          (private) method already holds a lock
PTA405    locks acquired in ``__del__`` / signal-handler / ``atexit``
          context — a non-reentrant lock there can interrupt its own
          holder (the FlightRecorder SIGTERM self-deadlock class);
          reentrant locks pass
PTA406    queue ``get``/``task_done`` imbalance: a ``task_done`` that
          an exception between it and its ``get`` can skip (not in a
          ``finally``), or a ``join()`` on a queue whose consumers
          never call ``task_done``
PTA407    daemon thread on a crash-safe-write path (``atomic_write``):
          interpreter exit can kill it mid-write — safe only because
          (and only while) the write is tmp+rename
========  ==============================================================

The **runtime half** is ``framework/locks.py``: the same held-before
graph rebuilt from what actually runs, under ``FLAGS_lock_watchdog``.
Locks created as ``locks.lock("name")`` are modeled under that literal
name, so a PTA401 finding and the watchdog's ``locks.cycle`` flight
event name the same cycle — the static model is validated by the
dynamic one and vice versa (the CI watchdog lane pins this on a
committed inversion fixture).

Suppression: the shared ``# pta: disable=PTA4xx`` pragmas, header-span
aware (a pragma on any line of a multi-line ``with`` header or on a
decorator line counts).  CLI: ``python tools/prog_lint.py --threads
<targets>``.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.framework.analysis.diagnostics import (
    Diagnostic, Report, Severity, parse_suppressions, register_rule)

__all__ = ["analyze_files", "analyze_sources", "lint_threads_source",
           "LockModel"]

register_rule("PTA401", "lock-order inversion (static acquisition "
              "cycle)", Severity.ERROR, "threads")
register_rule("PTA402", "blocking call under a held lock",
              Severity.WARNING, "threads")
register_rule("PTA403", "unguarded shared write from a thread/executor "
              "task", Severity.WARNING, "threads")
register_rule("PTA404", "check-then-act lazy init without the lock",
              Severity.WARNING, "threads")
register_rule("PTA405", "lock acquired in __del__/signal/atexit "
              "context", Severity.WARNING, "threads")
register_rule("PTA406", "queue get/task_done imbalance",
              Severity.WARNING, "threads")
register_rule("PTA407", "daemon thread on a crash-safe write path",
              Severity.WARNING, "threads")

_LOCK_CTORS = {"Lock": False, "RLock": True}
_WRAPPER_CTORS = {"lock": False, "rlock": True}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                "JoinableQueue"}
_POOL_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor", "Pool"}
_BLOCKING_ATTRS = {"recv": "socket.recv", "recv_into": "socket.recv",
                   "accept": "socket.accept"}
_SUBPROCESS_CALLS = {"run", "check_output", "check_call", "call",
                     "Popen"}


def _last_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        return _last_name(node.func)
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    return node.id if isinstance(node, ast.Name) else None


def _dotted(node: ast.AST) -> Optional[str]:
    """Canonical dotted form of a Name/Attribute chain (ctx-blind)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


@dataclass(frozen=True)
class LockDef:
    key: str                       # graph node name (shared == same key)
    reentrant: bool
    file: str
    line: int


@dataclass
class _CallSite:
    expr: ast.Call
    node: ast.AST                  # anchor for diagnostics
    held: Tuple[str, ...]          # lock keys held at the site


@dataclass
class _Func:
    key: str
    module: str
    cls: Optional[str]
    name: str
    node: ast.AST
    file: str
    acquires: List[Tuple[str, ast.AST, Tuple[str, ...]]] = \
        field(default_factory=list)      # (lock key, node, held-before)
    calls: List[_CallSite] = field(default_factory=list)
    blocking: List[Tuple[str, ast.AST, Tuple[str, ...], str]] = \
        field(default_factory=list)      # (kind, node, held, detail)
    self_writes: List[Tuple[str, ast.AST, bool]] = \
        field(default_factory=list)      # (attr, node, under_lock)
    self_reads: Set[str] = field(default_factory=set)
    lazy_inits: List[Tuple[str, ast.AST, bool, str]] = \
        field(default_factory=list)      # (desc, node, under_lock, kind)
    q_gets: List[Tuple[str, ast.AST]] = field(default_factory=list)
    q_task_dones: List[Tuple[str, ast.AST, bool]] = \
        field(default_factory=list)      # (queue, node, in_finally)
    q_joins: List[Tuple[str, ast.AST]] = field(default_factory=list)
    spawns: List[Tuple[Optional[str], bool, ast.AST, str]] = \
        field(default_factory=list)      # (target key, daemon, node, how)
    crash_safe_writes: List[ast.AST] = field(default_factory=list)
    local_funcs: Dict[str, str] = field(default_factory=dict)
    nested: List[str] = field(default_factory=list)
    declared_global: Set[str] = field(default_factory=set)
    finalizer: Optional[str] = None      # "__del__"|"signal"|"atexit"


@dataclass
class _Class:
    key: str
    module: str
    name: str
    methods: Dict[str, str] = field(default_factory=dict)  # name -> fkey
    lock_attrs: Dict[str, LockDef] = field(default_factory=dict)
    queue_attrs: Set[str] = field(default_factory=set)
    thread_attrs: Set[str] = field(default_factory=set)
    pool_attrs: Set[str] = field(default_factory=set)
    attr_instances: Dict[str, str] = field(default_factory=dict)
    # same-class call sites per method: method name -> [under_lock?]
    intra_calls: Dict[str, List[bool]] = field(default_factory=dict)


@dataclass
class _Module:
    key: str
    file: str
    imports: Dict[str, str] = field(default_factory=dict)
    from_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    classes: Dict[str, _Class] = field(default_factory=dict)
    functions: Dict[str, str] = field(default_factory=dict)
    locks: Dict[str, LockDef] = field(default_factory=dict)
    queues: Set[str] = field(default_factory=set)
    instances: Dict[str, str] = field(default_factory=dict)  # name->cls key
    globals: Set[str] = field(default_factory=set)
    source: str = ""


class LockModel:
    """The whole-repo model the passes run over: every module's symbol
    tables plus per-function summaries."""

    def __init__(self):
        self.modules: Dict[str, _Module] = {}
        self.funcs: Dict[str, _Func] = {}
        self.locks: Dict[str, LockDef] = {}
        self.callees: Dict[str, Set[str]] = {}       # resolved call graph
        self.callers: Dict[str, Set[str]] = {}

    def lock_def(self, key: str) -> Optional[LockDef]:
        return self.locks.get(key)


# ---------------------------------------------------------------------------
# phase 1: per-module symbol tables
# ---------------------------------------------------------------------------

def _module_name(path: str, repo_root: Optional[str]) -> str:
    p = os.path.normpath(os.path.abspath(path))
    parts = p.replace("\\", "/").split("/")
    if "paddle_tpu" in parts:
        parts = parts[parts.index("paddle_tpu"):]
    else:
        parts = parts[-1:]
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1] or ["<root>"]
    return ".".join(parts)


def _lock_ctor(expr: ast.AST, imports: Dict[str, str],
               from_imports: Dict[str, Tuple[str, str]]
               ) -> Optional[Tuple[bool, Optional[str]]]:
    """(reentrant, explicit name) when ``expr`` constructs a lock."""
    if not isinstance(expr, ast.Call):
        return None
    fn = expr.func
    name = _last_name(fn)
    if name in _LOCK_CTORS:
        root = _root_name(fn)
        if isinstance(fn, ast.Name) or root in ("threading", "_threading"):
            return _LOCK_CTORS[name], None
        return None
    if name in _WRAPPER_CTORS:
        root = _root_name(fn)
        ok = isinstance(fn, ast.Name) or root == "locks" or \
            imports.get(root, "").endswith("locks") or \
            from_imports.get(root or "", ("", ""))[0].endswith("locks")
        if not ok:
            return None
        lit = None
        if expr.args and isinstance(expr.args[0], ast.Constant) and \
                isinstance(expr.args[0].value, str):
            lit = expr.args[0].value
        return _WRAPPER_CTORS[name], lit
    return None


def _is_queue_ctor(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Call) and \
        _last_name(expr.func) in _QUEUE_CTORS


def _is_pool_ctor(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Call) and \
        _last_name(expr.func) in _POOL_CTORS


def _is_thread_ctor(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Call) and \
        _last_name(expr.func) == "Thread"


class _ModuleScanner:
    """Builds one module's symbol tables (no statement semantics yet)."""

    def __init__(self, model: LockModel, key: str, file: str,
                 tree: ast.Module, source: str):
        self.model = model
        self.m = _Module(key=key, file=file, source=source)
        model.modules[key] = self.m
        self.tree = tree

    def scan(self):
        m = self.m
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    m.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    m.from_imports[a.asname or a.name] = (node.module,
                                                          a.name)
        for stmt in self.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self._scan_class(stmt)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                m.functions[stmt.name] = f"{m.key}.{stmt.name}"
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        m.globals.add(t.id)
                        self._module_binding(t.id, stmt.value, stmt)
            elif isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                m.globals.add(stmt.target.id)
                if stmt.value is not None:
                    self._module_binding(stmt.target.id, stmt.value, stmt)

    def _module_binding(self, name: str, value: ast.AST, stmt: ast.stmt):
        m = self.m
        lk = _lock_ctor(value, m.imports, m.from_imports)
        if lk is not None:
            reentrant, lit = lk
            d = LockDef(lit or f"{m.key}.{name}", reentrant, m.file,
                        stmt.lineno)
            m.locks[name] = d
            self.model.locks.setdefault(d.key, d)
            return
        if _is_queue_ctor(value):
            m.queues.add(name)
            return
        if isinstance(value, ast.Call) and \
                isinstance(value.func, ast.Name):
            m.instances[name] = f"{m.key}.{value.func.id}"

    def _scan_class(self, cls: ast.ClassDef):
        m = self.m
        c = _Class(key=f"{m.key}.{cls.name}", module=m.key, name=cls.name)
        m.classes[cls.name] = c
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                c.methods[stmt.name] = f"{c.key}.{stmt.name}"
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self._attr_binding(c, t.id, stmt.value, stmt)
        # self.X = ... bindings anywhere in the class's methods
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(meth):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        self._attr_binding(c, t.attr, node.value, node)

    def _attr_binding(self, c: _Class, attr: str, value: ast.AST,
                      stmt: ast.stmt):
        m = self.m
        lk = _lock_ctor(value, m.imports, m.from_imports)
        if lk is not None:
            reentrant, lit = lk
            d = LockDef(lit or f"{c.key}.{attr}", reentrant, m.file,
                        stmt.lineno)
            c.lock_attrs.setdefault(attr, d)
            self.model.locks.setdefault(d.key, d)
            return
        if _is_queue_ctor(value):
            c.queue_attrs.add(attr)
        elif _is_pool_ctor(value):
            c.pool_attrs.add(attr)
        elif _is_thread_ctor(value):
            c.thread_attrs.add(attr)
        elif isinstance(value, ast.Call) and \
                isinstance(value.func, ast.Name) and \
                value.func.id[:1].isupper():
            c.attr_instances.setdefault(attr, value.func.id)


# ---------------------------------------------------------------------------
# phase 2: per-function statement walk (held-stack accurate)
# ---------------------------------------------------------------------------

class _FuncWalker:
    def __init__(self, model: LockModel, mod: _Module,
                 cls: Optional[_Class], fn: ast.AST, key: str):
        self.model = model
        self.mod = mod
        self.cls = cls
        self.f = _Func(key=key, module=mod.key,
                       cls=cls.name if cls else None,
                       name=getattr(fn, "name", "<lambda>"), node=fn,
                       file=mod.file)
        model.funcs[key] = self.f
        if cls is not None and self.f.name == "__del__":
            self.f.finalizer = "__del__"
        for dec in getattr(fn, "decorator_list", ()):
            if _dotted(dec) == "atexit.register":
                model.callees.setdefault("<finalizers>", set()).add(key)
        self.local_locks: Dict[str, LockDef] = {}
        self.local_queues: Set[str] = set()
        self.local_pools: Set[str] = set()
        self.local_threads: Set[str] = set()
        self.held: List[Tuple[str, bool]] = []   # (key, via_with)
        self.finally_depth = 0

    # -- resolution ---------------------------------------------------------
    def resolve_lock(self, expr: ast.AST) -> Optional[LockDef]:
        if isinstance(expr, ast.Name):
            if expr.id in self.local_locks:
                return self.local_locks[expr.id]
            return self.mod.locks.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base in ("self", "cls") and self.cls is not None:
                return self.cls.lock_attrs.get(expr.attr)
            # Class._lock via the class name (classmethod idiom)
            c = self.mod.classes.get(base)
            if c is not None:
                return c.lock_attrs.get(expr.attr)
            # other_module._lock
            mk = self.mod.imports.get(base)
            om = self.model.modules.get(mk) if mk else None
            if om is not None:
                return om.locks.get(expr.attr)
        return None

    def _is_queue(self, expr: ast.AST) -> Optional[str]:
        """A canonical queue id when ``expr`` denotes a known queue (or
        is queue-ish by name), else None."""
        if isinstance(expr, ast.Name):
            if expr.id in self.local_queues or expr.id in self.mod.queues:
                return f"{self.f.key}.{expr.id}" \
                    if expr.id in self.local_queues \
                    else f"{self.mod.key}.{expr.id}"
            if expr.id in ("q", "_q", "queue") or \
                    expr.id.endswith("queue"):
                return f"{self.f.key}.{expr.id}"
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self.cls is not None:
            if expr.attr in self.cls.queue_attrs:
                return f"{self.cls.key}.{expr.attr}"
            if expr.attr in ("q", "_q") or expr.attr.endswith("queue"):
                return f"{self.cls.key}.{expr.attr}"
        return None

    def _is_threadlike(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            n = expr.id
            return n in self.local_threads or "thread" in n or \
                "proc" in n
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self.cls is not None:
            n = expr.attr
            return n in self.cls.thread_attrs or "thread" in n or \
                "proc" in n
        return False

    def _is_pool(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            n = expr.id
            return n in self.local_pools or "pool" in n or \
                "executor" in n.lower()
        if isinstance(expr, ast.Attribute):
            n = expr.attr
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and self.cls is not None and \
                    n in self.cls.pool_attrs:
                return True
            return "pool" in n or "executor" in n.lower()
        return False

    def _target_ref(self, expr: ast.AST) -> Optional[str]:
        """Resolve a thread/executor *target expression* to a func key."""
        if isinstance(expr, ast.Name):
            if expr.id in self.f.local_funcs:
                return self.f.local_funcs[expr.id]
            if expr.id in self.mod.functions:
                return self.mod.functions[expr.id]
            fi = self.mod.from_imports.get(expr.id)
            if fi is not None:
                return f"{fi[0]}.{fi[1]}"
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and self.cls is not None:
                return self.cls.methods.get(expr.attr)
            mk = self.mod.imports.get(expr.value.id)
            if mk is not None:
                return f"{mk}.{expr.attr}"
        return None

    # -- the walk -----------------------------------------------------------
    def run(self):
        fn = self.f.node
        for stmt in fn.body:
            self.visit_stmt(stmt)
        return self.f

    def _held_keys(self) -> Tuple[str, ...]:
        return tuple(k for k, _ in self.held)

    def visit_stmt(self, stmt: ast.stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = f"{self.f.key}.{stmt.name}"
            self.f.local_funcs[stmt.name] = key
            self.f.nested.append(key)
            sub = _FuncWalker(self.model, self.mod, self.cls, stmt, key)
            sub.f.local_funcs.update(self.f.local_funcs)
            sub.run()
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, ast.Global):
            self.f.declared_global.update(stmt.names)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                d = self.resolve_lock(item.context_expr)
                if d is None and isinstance(item.context_expr, ast.Call):
                    inner = item.context_expr.func
                    # lock.acquire()-style context or cm-returning call
                    d = self.resolve_lock(inner) \
                        if isinstance(inner, ast.Attribute) and \
                        _last_name(inner) in ("acquire",) else None
                    if d is None:
                        self.visit_expr(item.context_expr, stmt)
                if d is not None:
                    self.f.acquires.append((d.key, stmt,
                                            self._held_keys()))
                    self.held.append((d.key, True))
                    pushed += 1
                elif not isinstance(item.context_expr, ast.Call):
                    self.visit_expr(item.context_expr, stmt)
            for s in stmt.body:
                self.visit_stmt(s)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body:
                self.visit_stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    self.visit_stmt(s)
            for s in stmt.orelse:
                self.visit_stmt(s)
            self.finally_depth += 1
            for s in stmt.finalbody:
                self.visit_stmt(s)
            self.finally_depth -= 1
            return
        if isinstance(stmt, ast.If):
            self._check_lazy_init(stmt)
            self.visit_expr(stmt.test, stmt)
            for s in stmt.body + stmt.orelse:
                self.visit_stmt(s)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.visit_expr(stmt.iter, stmt)
            for s in stmt.body + stmt.orelse:
                self.visit_stmt(s)
            return
        if isinstance(stmt, ast.While):
            self.visit_expr(stmt.test, stmt)
            for s in stmt.body + stmt.orelse:
                self.visit_stmt(s)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = getattr(stmt, "value", None)
            if value is not None:
                self._check_binding(stmt, value)
                self.visit_expr(value, stmt)
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                self._note_store(t, stmt)
            return
        if isinstance(stmt, ast.Expr):
            self.visit_expr(stmt.value, stmt)
            return
        if isinstance(stmt, (ast.Return, ast.Raise)):
            v = stmt.value if isinstance(stmt, ast.Return) else stmt.exc
            if v is not None:
                self.visit_expr(v, stmt)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self.visit_expr(child, stmt)

    # -- bindings / stores --------------------------------------------------
    def _check_binding(self, stmt: ast.stmt, value: ast.AST):
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [getattr(stmt, "target", None)]
        name = targets[0].id if targets and isinstance(targets[0],
                                                       ast.Name) else None
        if name is None:
            # `lock, seq_box = threading.Lock(), [0]` — pairwise displays
            if targets and isinstance(targets[0], ast.Tuple) and \
                    isinstance(value, ast.Tuple) and \
                    len(targets[0].elts) == len(value.elts):
                for t, v in zip(targets[0].elts, value.elts):
                    if isinstance(t, ast.Name):
                        self._bind_local(t.id, v, stmt)
            return
        self._bind_local(name, value, stmt)

    def _bind_local(self, name: str, value: ast.AST, stmt: ast.stmt):
        lk = _lock_ctor(value, self.mod.imports, self.mod.from_imports)
        if lk is not None:
            reentrant, lit = lk
            d = LockDef(lit or f"{self.f.key}.{name}", reentrant,
                        self.mod.file, stmt.lineno)
            self.local_locks[name] = d
            self.model.locks.setdefault(d.key, d)
        elif _is_queue_ctor(value):
            self.local_queues.add(name)
        elif _is_pool_ctor(value):
            self.local_pools.add(name)
        elif _is_thread_ctor(value):
            self.local_threads.add(name)
            self._note_spawn(value, stmt)

    def _note_store(self, target: ast.AST, stmt: ast.stmt):
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._note_store(e, stmt)
            return
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            self.f.self_writes.append((target.attr, stmt,
                                       bool(self.held)))

    def _check_lazy_init(self, stmt: ast.If):
        """``if X is None: X = ...`` / ``if not X: X = ...`` on shared
        state (self/cls attribute or module global)."""
        test = stmt.test
        target = None
        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.ops[0], ast.Is) and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            target = test.left
        elif isinstance(test, ast.UnaryOp) and \
                isinstance(test.op, ast.Not):
            target = test.operand
        if target is None:
            return
        kind = desc = None
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id in ("self", "cls"):
            kind, desc = "attr", f"{target.value.id}.{target.attr}"
        elif isinstance(target, ast.Name) and (
                target.id in self.mod.globals or
                target.id in self.f.declared_global):
            kind, desc = "global", target.id
        if desc is None:
            return
        # the body must assign the same target (ctx-insensitive compare:
        # the test reads it, the body stores it)
        want = _dotted(target)
        assigns = False
        for s in stmt.body:
            for node in ast.walk(s):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    tg = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in tg:
                        if _dotted(t) == want:
                            assigns = True
        if not assigns:
            return
        # double-checked locking: every assignment to the target sits
        # inside a `with <known lock>:` of the body (the unlocked outer
        # check is the fast path, the locked re-check the guard) — the
        # canonical correct idiom, not a finding
        guarded_spans = []
        for s in stmt.body:
            for node in ast.walk(s):
                if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                        self.resolve_lock(i.context_expr) is not None
                        for i in node.items):
                    guarded_spans.append(node)

        def under_guard(n: ast.AST) -> bool:
            return any(n in set(ast.walk(g)) for g in guarded_spans)

        all_guarded = True
        for s in stmt.body:
            for node in ast.walk(s):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    tg = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    if any(_dotted(t) == want for t in tg) and \
                            not under_guard(node):
                        all_guarded = False
        if all_guarded:
            return
        self.f.lazy_inits.append((desc, stmt, bool(self.held), kind))

    # -- expressions (calls) ------------------------------------------------
    def visit_expr(self, expr: ast.AST, anchor: ast.stmt):
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and \
                    isinstance(node.ctx, ast.Load):
                self.f.self_reads.add(node.attr)
            if not isinstance(node, ast.Call):
                continue
            self._visit_call(node, anchor)

    def _visit_call(self, call: ast.Call, anchor: ast.stmt):
        fn = call.func
        name = _last_name(fn)
        held = self._held_keys()
        # explicit acquire/release
        if isinstance(fn, ast.Attribute) and name in ("acquire",
                                                      "release"):
            d = self.resolve_lock(fn.value)
            if d is not None:
                if name == "acquire":
                    self.f.acquires.append((d.key, anchor, held))
                    self.held.append((d.key, False))
                else:
                    for i in range(len(self.held) - 1, -1, -1):
                        if self.held[i][0] == d.key:
                            self.held.pop(i)
                            break
                return
        # thread spawn / executor submit
        if _is_thread_ctor(call):
            self._note_spawn(call, anchor)
        elif isinstance(fn, ast.Attribute) and \
                name in ("submit", "map") and self._is_pool(fn.value):
            if call.args:
                ref = self._target_ref(call.args[0])
                self.f.spawns.append((ref, False, anchor, name))
        # finalizer registration (resolved refs are filtered against
        # the final func table in phase 3 — registration may lexically
        # precede or follow the handler's def)
        root = _root_name(fn)
        if name == "register" and (
                root == "atexit" or
                self.mod.imports.get(root or "") == "atexit") and \
                call.args:
            ref = self._target_ref(call.args[0])
            if ref is not None:
                self.model.callees.setdefault(
                    "<finalizers>", set()).add(ref)
        if name == "signal" and (
                root in ("signal", "_signal") or
                self.mod.imports.get(root or "") == "signal") and \
                len(call.args) >= 2:
            ref = self._target_ref(call.args[1])
            if ref is not None:
                self.model.callees.setdefault(
                    "<signal-handlers>", set()).add(ref)
        # blocking shapes
        self._check_blocking(call, fn, name, anchor, held)
        # queue protocol
        if isinstance(fn, ast.Attribute):
            qid = self._is_queue(fn.value)
            if qid is not None:
                if name == "get":
                    self.f.q_gets.append((qid, anchor))
                elif name == "task_done":
                    self.f.q_task_dones.append(
                        (qid, anchor, self.finally_depth > 0))
                elif name == "join":
                    self.f.q_joins.append((qid, anchor))
        # crash-safe write path
        if name == "atomic_write":
            self.f.crash_safe_writes.append(anchor)
        # resolvable call site (for the call graph)
        self.f.calls.append(_CallSite(expr=call, node=anchor, held=held))

    def _check_blocking(self, call: ast.Call, fn: ast.AST,
                        name: Optional[str], anchor: ast.stmt,
                        held: Tuple[str, ...]):
        f = self.f
        if isinstance(fn, ast.Attribute) and name in _BLOCKING_ATTRS:
            f.blocking.append((_BLOCKING_ATTRS[name], anchor, held,
                               ast.unparse(fn)))
            return
        root = _root_name(fn)
        if root == "subprocess" or (
                isinstance(fn, ast.Name) and name in _SUBPROCESS_CALLS
                and self.mod.from_imports.get(name, ("",))[0]
                == "subprocess"):
            f.blocking.append(("subprocess", anchor, held,
                               ast.unparse(fn)))
            return
        if name == "fsync":
            f.blocking.append(("fsync", anchor, held, ast.unparse(fn)))
            return
        if isinstance(fn, ast.Attribute) and name == "get":
            qid = self._is_queue(fn.value)
            if qid is not None and not self._get_bounded(call):
                f.blocking.append(("Queue.get (no timeout)", anchor,
                                   held, ast.unparse(fn)))
            return
        if isinstance(fn, ast.Attribute) and name == "join":
            if self._is_queue(fn.value) is not None or \
                    self._is_threadlike(fn.value) or \
                    self._is_pool(fn.value):
                if not call.args and not any(
                        k.arg == "timeout" for k in call.keywords):
                    f.blocking.append(("join (no timeout)", anchor,
                                       held, ast.unparse(fn)))

    @staticmethod
    def _get_bounded(call: ast.Call) -> bool:
        if any(k.arg == "timeout" and not (
                isinstance(k.value, ast.Constant) and
                k.value.value is None) for k in call.keywords):
            return True
        # get(False) / get(block=False) never blocks
        if call.args and isinstance(call.args[0], ast.Constant) and \
                call.args[0].value is False:
            return True
        return any(k.arg == "block" and isinstance(k.value, ast.Constant)
                   and k.value.value is False for k in call.keywords)

    def _note_spawn(self, call: ast.Call, anchor: ast.stmt):
        target = daemon = None
        for k in call.keywords:
            if k.arg == "target":
                target = k.value
            elif k.arg == "daemon" and isinstance(k.value, ast.Constant):
                daemon = bool(k.value.value)
        ref = self._target_ref(target) if target is not None else None
        self.f.spawns.append((ref, bool(daemon), anchor, "Thread"))


# ---------------------------------------------------------------------------
# phase 3: call resolution, summaries, and the passes
# ---------------------------------------------------------------------------

class _Analyzer:
    def __init__(self, model: LockModel):
        self.model = model
        self.report = Report()
        self._sups: Dict[str, object] = {}

    # -- emission -----------------------------------------------------------
    def _suppressed(self, rule: str, file: str, node: ast.AST) -> bool:
        sup = self._sups.get(file)
        if sup is None:
            mod = next((m for m in self.model.modules.values()
                        if m.file == file), None)
            sup = parse_suppressions(mod.source if mod else "")
            self._sups[file] = sup
        return not sup.allows_node(rule, node)

    def emit(self, rule: str, file: str, node: ast.AST, message: str,
             severity: Severity, hint: Optional[str] = None):
        if self._suppressed(rule, file, node):
            return
        self.report.add(Diagnostic(
            rule, message, severity, file=file,
            line=getattr(node, "lineno", None),
            col=getattr(node, "col_offset", None), hint=hint))

    # -- call-graph resolution ----------------------------------------------
    def resolve_call(self, f: _Func, call: ast.Call) -> Optional[str]:
        fn = call.func
        mod = self.model.modules[f.module]
        cls = mod.classes.get(f.cls) if f.cls else None
        if isinstance(fn, ast.Name):
            n = fn.id
            if n in f.local_funcs:
                return f.local_funcs[n]
            if n in mod.functions:
                return mod.functions[n]
            fi = mod.from_imports.get(n)
            if fi is not None:
                key = f"{fi[0]}.{fi[1]}"
                if key in self.model.funcs:
                    return key
            return None
        if not (isinstance(fn, ast.Attribute) and
                isinstance(fn.value, (ast.Name, ast.Attribute))):
            return None
        meth = fn.attr
        base = fn.value
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                key = cls.methods.get(meth)
                if key is not None:
                    cls.intra_calls.setdefault(meth, [])
                    return key
                return None
            # module alias:  monitor.stat_add(...)
            mk = mod.imports.get(base.id)
            if mk is None and base.id in mod.from_imports:
                fmk, attr = mod.from_imports[base.id]
                # from pkg import module  /  from module import instance
                cand = f"{fmk}.{attr}"
                if cand in self.model.modules:
                    mk = cand
                else:
                    om = self.model.modules.get(fmk)
                    if om is not None and attr in om.instances:
                        ckey = om.instances[attr]
                        return self._method_of(ckey, meth)
            if mk is not None:
                om = self.model.modules.get(mk)
                if om is not None:
                    if meth in om.functions:
                        return om.functions[meth]
                    if meth in om.instances:      # mod.inst(...)? rare
                        return None
            # module-level instance in the same module
            if base.id in mod.instances:
                return self._method_of(mod.instances[base.id], meth)
            # local/class instance via  x = ClassName(...)
            return None
        # self.attr.method(): instance field of a known class
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and cls is not None:
            cname = cls.attr_instances.get(base.attr)
            if cname is not None:
                for m in self.model.modules.values():
                    if cname in m.classes:
                        return m.classes[cname].methods.get(meth)
        return None

    def _method_of(self, class_key: str, meth: str) -> Optional[str]:
        for m in self.model.modules.values():
            for c in m.classes.values():
                if c.key == class_key:
                    return c.methods.get(meth)
        return None

    # -- summaries ----------------------------------------------------------
    def build(self):
        model = self.model
        # resolve every call site once
        self.resolved: Dict[Tuple[str, int], Optional[str]] = {}
        for f in model.funcs.values():
            for cs in f.calls:
                key = self.resolve_call(f, cs.expr)
                self.resolved[(f.key, id(cs.expr))] = key
                if key is not None:
                    model.callees.setdefault(f.key, set()).add(key)
                    model.callers.setdefault(key, set()).add(f.key)
                    # same-class call-site lock context (PTA404 exemption)
                    cf = model.funcs.get(key)
                    if cf is not None and cf.cls == f.cls and \
                            cf.module == f.module and f.cls is not None:
                        cls = model.modules[f.module].classes[f.cls]
                        cls.intra_calls.setdefault(
                            cf.name, []).append(bool(cs.held))
        # effective lock sets (direct + nested defs + callees), fixpoint
        self.eff: Dict[str, Set[str]] = {
            k: {a for a, _, _ in f.acquires} for k, f in
            model.funcs.items()}
        for k, f in model.funcs.items():
            for nk in f.nested:
                self.eff[k] |= self.eff.get(nk, set())
        self._fixpoint(self.eff)
        # blocking summaries, fixpoint over the same graph
        self.blocks: Dict[str, Set[str]] = {
            k: {kind for kind, _, _, _ in f.blocking}
            for k, f in model.funcs.items()}
        for k, f in model.funcs.items():
            for nk in f.nested:
                pass          # nested defs run later, not on this path
        self._fixpoint(self.blocks)

    def _fixpoint(self, table: Dict[str, Set[str]],
                  rounds: Optional[int] = None):
        # converges in at most |funcs| rounds (summaries only grow and
        # propagate one call-graph level per sweep); the cap is a
        # cycle-safety bound, never a silent truncation of deep chains
        if rounds is None:
            rounds = len(self.model.funcs) + 1
        for _ in range(max(1, rounds)):
            changed = False
            for k, callees in self.model.callees.items():
                if k.startswith("<"):
                    continue
                cur = table.setdefault(k, set())
                before = len(cur)
                for c in callees:
                    cur |= table.get(c, set())
                changed |= len(cur) != before
            if not changed:
                return

    # -- PTA401: acquisition graph + cycles ---------------------------------
    def check_lock_order(self):
        model = self.model
        edges: Dict[str, Dict[str, Tuple[str, ast.AST]]] = {}

        def add_edge(a: str, b: str, file: str, node: ast.AST):
            slot = edges.setdefault(a, {})
            prev = slot.get(b)
            if prev is None or (file, getattr(node, "lineno", 0)) < \
                    (prev[0], getattr(prev[1], "lineno", 0)):
                slot[b] = (file, node)

        for f in model.funcs.values():
            for lock_key, node, held in f.acquires:
                for h in held:
                    if h != lock_key:
                        add_edge(h, lock_key, f.file, node)
                if lock_key in held:
                    # direct nested re-acquire: unconditional deadlock
                    # on a non-reentrant lock, no call graph needed
                    d = model.lock_def(lock_key)
                    if d is not None and not d.reentrant:
                        self.emit(
                            "PTA401", f.file, node,
                            f"self-deadlock: non-reentrant lock "
                            f"`{lock_key}` re-acquired while already "
                            "held on this path — the thread blocks on "
                            "itself unconditionally", Severity.ERROR,
                            hint="make it an rlock, or drop the inner "
                                 "acquisition")
            for cs in f.calls:
                if not cs.held:
                    continue
                callee = self.resolved.get((f.key, id(cs.expr)))
                if callee is None:
                    continue
                for lk in self.eff.get(callee, ()):
                    for h in cs.held:
                        if h != lk:
                            add_edge(h, lk, f.file, cs.node)
                    # self-deadlock: the held lock re-acquired downstream
                    for h in cs.held:
                        if lk == h:
                            d = model.lock_def(h)
                            if d is not None and not d.reentrant:
                                self.emit(
                                    "PTA401", f.file, cs.node,
                                    f"self-deadlock: non-reentrant lock "
                                    f"`{h}` is already held here and "
                                    f"`{callee}` (re)acquires it",
                                    Severity.ERROR,
                                    hint="make it an rlock, or hoist "
                                         "the call out of the locked "
                                         "region")
        # SCCs over the edge graph
        for cycle in _find_cycles({a: set(bs) for a, bs in
                                   edges.items()}):
            sites = []
            for a, b in zip(cycle, cycle[1:] + cycle[:1]):
                site = edges.get(a, {}).get(b)
                if site is not None:
                    sites.append((a, b, site))
            if not sites:
                continue
            # a pragma on ANY edge of the cycle breaks it: the user is
            # asserting that edge cannot race (e.g. runs before the
            # threads exist), which dissolves the whole cycle
            if any(self._suppressed("PTA401", fl, nd)
                   for _, _, (fl, nd) in sites):
                continue
            sites.sort(key=lambda s: (s[2][0],
                                      getattr(s[2][1], "lineno", 0)))
            a, b, (file, node) = sites[0]
            loop = " -> ".join(cycle + [cycle[0]])
            others = "; ".join(
                f"{x}->{y} at {fl}:{getattr(nd, 'lineno', '?')}"
                for x, y, (fl, nd) in sites[1:]) or "single edge"
            self.emit(
                "PTA401", file, node,
                f"lock-order inversion: static acquisition cycle "
                f"{loop} (this edge acquires `{b}` while holding "
                f"`{a}`; reverse edge(s): {others}) — two threads "
                "taking the ends in opposite order deadlock",
                Severity.ERROR,
                hint="pick one global order for these locks (the "
                     "runtime watchdog names the same cycle in a "
                     "locks.cycle flight event under "
                     "FLAGS_lock_watchdog)")

    # -- PTA402: blocking under a held lock ---------------------------------
    def check_blocking(self):
        for f in self.model.funcs.values():
            for kind, node, held, detail in f.blocking:
                if held:
                    self.emit(
                        "PTA402", f.file, node,
                        f"blocking call `{detail}` ({kind}) while "
                        f"holding `{held[-1]}` — every other thread "
                        "needing the lock stalls behind the I/O wait",
                        Severity.WARNING,
                        hint="narrow the lock scope, or bound the call "
                             "with a timeout")
            for cs in f.calls:
                if not cs.held:
                    continue
                callee = self.resolved.get((f.key, id(cs.expr)))
                if callee is None:
                    continue
                kinds = self.blocks.get(callee, ())
                if kinds:
                    self.emit(
                        "PTA402", f.file, cs.node,
                        f"call to `{callee}` while holding "
                        f"`{cs.held[-1]}` — the callee blocks "
                        f"({', '.join(sorted(kinds))})",
                        Severity.WARNING,
                        hint="narrow the lock scope, or bound the "
                             "callee's wait with a timeout")

    # -- PTA403: unguarded shared writes from threads -----------------------
    def check_thread_writes(self):
        model = self.model
        # thread-entry closure over the resolved call graph
        roots: Set[str] = set()
        for f in model.funcs.values():
            for ref, _daemon, _node, _how in f.spawns:
                if ref is not None:
                    roots.add(ref)
        thread_set = _closure(roots, model.callees)
        main_callers: Dict[str, bool] = {}
        for k in thread_set:
            main_callers[k] = any(c not in thread_set
                                  for c in model.callers.get(k, ()))
        for k in sorted(thread_set):
            f = model.funcs.get(k)
            if f is None or f.cls is None:
                continue
            cls = model.modules[f.module].classes.get(f.cls)
            if cls is None:
                continue
            for attr, node, under_lock in f.self_writes:
                if under_lock:
                    continue
                # a private method whose every same-class call site
                # holds a lock is guarded by its callers (the
                # FlightRecorder._buf idiom) — same exemption as PTA404
                if f.name.startswith("_"):
                    sites = cls.intra_calls.get(f.name, [])
                    if sites and all(sites):
                        continue
                shared = main_callers.get(k, False)
                if not shared:
                    for ok, other in (
                            (n, model.funcs.get(mk))
                            for n, mk in cls.methods.items()):
                        if other is None or other.key == k or \
                                other.key in thread_set or \
                                ok == "__init__":
                            continue
                        if attr in other.self_reads or any(
                                a == attr for a, _, _ in
                                other.self_writes):
                            shared = True
                            break
                if shared:
                    self.emit(
                        "PTA403", f.file, node,
                        f"`self.{attr}` written on a thread/executor "
                        f"path (`{f.key}`) with no lock held, and "
                        "touched from non-thread methods too — "
                        "concurrent read-modify-write loses updates",
                        Severity.WARNING,
                        hint="guard both sides with one lock, or keep "
                             "the attribute single-threaded")

    # -- PTA404: check-then-act lazy init -----------------------------------
    def check_lazy_init(self):
        model = self.model
        for f in model.funcs.values():
            mod = model.modules[f.module]
            cls = mod.classes.get(f.cls) if f.cls else None
            for desc, node, under_lock, kind in f.lazy_inits:
                if under_lock:
                    continue
                # shared-state scope gate: an attribute is a finding
                # only in a class that owns concurrency structure (its
                # own locks/queues/pools/threads); a module global only
                # in a module that owns locks.  A lockless value class
                # (Tensor) doing lazy init is not a thread hazard.
                if kind == "attr":
                    if cls is None or not (
                            cls.lock_attrs or cls.queue_attrs or
                            cls.pool_attrs or cls.thread_attrs):
                        continue
                elif not mod.locks:
                    continue
                # exemption: a private method whose every same-class
                # call site holds a lock IS guarded — by its callers
                if cls is not None and f.name.startswith("_"):
                    sites = cls.intra_calls.get(f.name, [])
                    if sites and all(sites):
                        continue
                self.emit(
                    "PTA404", f.file, node,
                    f"check-then-act lazy init of `{desc}` outside any "
                    "lock — two threads can both see it unset and both "
                    "initialize (lost state, double resource)",
                    Severity.WARNING,
                    hint="initialize under the owning lock "
                         "(double-checked), or eagerly in __init__")

    # -- PTA405: locks in finalizer context ---------------------------------
    def check_finalizer_locks(self):
        model = self.model
        roots = {k for k, f in model.funcs.items() if f.finalizer}
        roots |= model.callees.get("<finalizers>", set())
        roots |= model.callees.get("<signal-handlers>", set())
        roots = {r for r in roots if r in model.funcs}
        for r in sorted(roots):
            f = model.funcs[r]
            ctx = f.finalizer or (
                "signal handler" if r in model.callees.get(
                    "<signal-handlers>", ()) else "atexit")
            bad = []
            for k in sorted(_closure({r}, model.callees)):
                for lk in sorted({a for a, _, _ in
                                  model.funcs[k].acquires}
                                 if k in model.funcs else ()):
                    d = model.lock_def(lk)
                    if d is not None and not d.reentrant and \
                            lk not in bad:
                        bad.append(lk)
            if bad:
                self.emit(
                    "PTA405", f.file, f.node,
                    f"`{f.name}` runs in {ctx} context and (possibly "
                    f"transitively) acquires non-reentrant lock(s) "
                    f"{', '.join(bad)} — if the interrupted thread "
                    "already holds one, the process self-deadlocks "
                    "(the FlightRecorder SIGTERM bug class)",
                    Severity.WARNING,
                    hint="use a reentrant lock (locks.rlock) on every "
                         "lock a finalizer path can touch, or defer "
                         "the work out of the handler")

    # -- PTA406: queue get/task_done imbalance ------------------------------
    def check_queue_protocol(self):
        model = self.model
        gets: Dict[str, List[Tuple[_Func, ast.AST]]] = {}
        dones: Dict[str, List[Tuple[_Func, ast.AST, bool]]] = {}
        joins: Dict[str, List[Tuple[_Func, ast.AST]]] = {}
        for f in model.funcs.values():
            for q, node in f.q_gets:
                gets.setdefault(q, []).append((f, node))
            for q, node, fin in f.q_task_dones:
                dones.setdefault(q, []).append((f, node, fin))
            for q, node in f.q_joins:
                joins.setdefault(q, []).append((f, node))
        for q, dlist in dones.items():
            if q not in gets:
                continue
            for f, node, in_finally in dlist:
                if not in_finally:
                    self.emit(
                        "PTA406", f.file, node,
                        f"`task_done()` on `{q}` outside a finally: an "
                        "exception between get() and task_done() "
                        "undercounts, and join() waits forever",
                        Severity.WARNING,
                        hint="call task_done() in a try/finally around "
                             "the work after get()")
        for q, jlist in joins.items():
            if q in gets and q not in dones:
                for f, node in jlist:
                    self.emit(
                        "PTA406", f.file, node,
                        f"`join()` on `{q}` but its consumers never "
                        "call task_done() — join() blocks forever "
                        "once anything was enqueued",
                        Severity.WARNING,
                        hint="pair every get() with task_done(), or "
                             "join the worker thread instead")

    # -- PTA407: daemon threads on crash-safe write paths -------------------
    def check_daemon_writers(self):
        model = self.model
        for f in model.funcs.values():
            for ref, daemon, node, how in f.spawns:
                if not daemon or ref is None:
                    continue
                for k in sorted(_closure({ref}, model.callees)):
                    kf = model.funcs.get(k)
                    if kf is not None and kf.crash_safe_writes:
                        self.emit(
                            "PTA407", f.file, node,
                            f"daemon thread target `{ref}` reaches a "
                            f"crash-safe write (`atomic_write` in "
                            f"`{k}`) — interpreter exit kills daemon "
                            "threads mid-call; this is safe ONLY "
                            "because the write is tmp+rename",
                            Severity.WARNING,
                            hint="make the thread non-daemon with a "
                                 "bounded join on shutdown, or accept "
                                 "torn-tmp garbage and say so with a "
                                 "pragma")
                        break


def _closure(roots: Set[str], callees: Dict[str, Set[str]]) -> Set[str]:
    out = set(roots)
    stack = list(roots)
    while stack:
        k = stack.pop()
        for c in callees.get(k, ()):
            if c not in out:
                out.add(c)
                stack.append(c)
    return out


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Cycles in the acquisition digraph: one representative simple
    cycle per non-trivial SCC (iterative Tarjan), deterministic order."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []
    nodes = sorted(set(graph) | {b for bs in graph.values() for b in bs})

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))
    # representative cycle per SCC: backtracking DFS for a TRUE simple
    # cycle through the smallest member (a greedy walk can dead-end and
    # return a path whose closing edge does not exist — a reported
    # "cycle" must be one the edge graph actually contains)
    cycles = []
    for scc in sccs:
        members = set(scc)
        start = scc[0]
        path = [start]
        on_path = {start}
        iters = [iter(sorted(n for n in graph.get(start, ())
                             if n in members))]
        while iters:
            advanced = False
            for nxt in iters[-1]:
                if nxt == start:
                    cycles.append(list(path))
                    iters = []
                    advanced = True
                    break
                if nxt not in on_path:
                    path.append(nxt)
                    on_path.add(nxt)
                    iters.append(iter(sorted(
                        n for n in graph.get(nxt, ()) if n in members)))
                    advanced = True
                    break
            if not advanced:
                iters.pop()
                on_path.discard(path.pop())
    return cycles


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def analyze_sources(sources: Dict[str, str],
                    disable: Sequence[str] = ()) -> Report:
    """Run the PTA4xx pass family over ``{filename: source}`` as ONE
    model (cross-file acquisition edges included)."""
    model = LockModel()
    scanners = []
    report = Report()
    for path in sorted(sources):
        src = sources[path]
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            report.add(Diagnostic(
                "PTA401", f"file does not parse: {e}", Severity.ERROR,
                file=path, line=e.lineno))
            continue
        key = _module_name(path, None)
        sc = _ModuleScanner(model, key, path, tree, src)
        sc.scan()
        scanners.append((sc, tree))
        report.files_seen.append(path)
    for sc, tree in scanners:
        mod = sc.m
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FuncWalker(model, mod, None, stmt,
                            f"{mod.key}.{stmt.name}").run()
            elif isinstance(stmt, ast.ClassDef):
                cls = mod.classes[stmt.name]
                for meth in stmt.body:
                    if isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        _FuncWalker(model, mod, cls, meth,
                                    f"{cls.key}.{meth.name}").run()
    an = _Analyzer(model)
    an.build()
    an.check_lock_order()
    an.check_blocking()
    an.check_thread_writes()
    an.check_lazy_init()
    an.check_finalizer_locks()
    an.check_queue_protocol()
    an.check_daemon_writers()
    out = an.report
    report.extend(out)
    return report.filter(disable=disable)


def analyze_files(paths: Sequence[str],
                  disable: Sequence[str] = ()) -> Report:
    """Concurrency-analyze a set of files as one whole-repo model.  An
    unreadable path degrades to one error diagnostic; every other
    file's findings survive."""
    sources = {}
    unreadable = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                sources[p] = f.read()
        except OSError as e:
            unreadable.append(Diagnostic(
                "PTA401", f"unreadable: {e}", Severity.ERROR, file=p))
    report = analyze_sources(sources, disable=disable)
    report.extend(d for d in unreadable if d.rule not in set(disable))
    return report


def lint_threads_source(source: str, filename: str = "fixture.py",
                        disable: Sequence[str] = ()) -> Report:
    """One-source convenience wrapper (tests)."""
    return analyze_sources({filename: source}, disable=disable)
