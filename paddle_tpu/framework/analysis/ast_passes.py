"""Jit-safety AST linter — the pre-trace half of the program analyzer.

Where the jaxpr passes see what a trace *produced*, this front end sees
what the source will *do to* a trace, before anything runs.  It extends
the dy2static machinery (paddle_tpu/jit/dy2static.py): the same
read/write collectors and outline-escape scanner that decide whether the
AST rewriter can convert a statement here decide how severe a finding is
— a tensor-dependent ``if`` that dy2static can outline is a warning
(lax.cond will handle it under ``to_static``), one it cannot outline
(return/break inside, attribute stores) is an error, because the trace
will either crash on a tracer-bool or silently bake one branch.

Taint model: inside a *jit-scope* function (decorated ``@to_static`` /
``@jax.jit``, a Layer's ``forward``, or nested in one), every parameter
is assumed traced.  Taint propagates through assignments and
expressions; metadata access (``.shape``/``.dtype``/``.ndim``) and
identity tests (``is None``) launder it — those are static facts under a
trace.  This mirrors the reference's dy2static static analysis
(dygraph_to_static/static_analysis.py NodeVarType inference), with
"traced" standing in for its VariableWrapper type.

Rules (stable IDs; see diagnostics.RULES):

========  ==============================================================
PTA201    Python ``if`` branching on a traced value
PTA202    Python ``while``/``for`` bounded by a traced value
PTA203    side effect / mutation under jit (attribute stores on self,
          global/nonlocal writes, print)
PTA204    tracer leak: a traced value stored where it outlives the
          trace (self attributes, globals, closure containers)
PTA205    ``numpy.*`` call on a traced array (concretizes or crashes)
PTA301    chaos fault-point call with no retry/backoff guard in scope
PTA302    chaos fault-point name not declared in the registry
========  ==============================================================
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.framework.analysis.diagnostics import (
    Diagnostic, Report, Severity, parse_suppressions, register_rule)
# deliberate reuse of the dy2static analysis machinery — the linter and
# the converter must agree on what is convertible, or the lint would
# promise rescues the rewriter cannot deliver
from paddle_tpu.jit.dy2static import _escapes, _NameCollector

__all__ = ["lint_source", "lint_file"]

register_rule("PTA201", "Python if on traced value", Severity.WARNING,
              "ast")
register_rule("PTA202", "Python loop bounded by traced value",
              Severity.WARNING, "ast")
register_rule("PTA203", "side effect under jit", Severity.WARNING, "ast")
register_rule("PTA204", "tracer leak", Severity.WARNING, "ast")
register_rule("PTA205", "numpy call on traced array", Severity.ERROR,
              "ast")
register_rule("PTA301", "unguarded chaos fault point", Severity.WARNING,
              "chaos")
register_rule("PTA302", "undeclared chaos fault point", Severity.ERROR,
              "chaos")

# attribute reads that yield static metadata, not a traced value
_METADATA_ATTRS = {"shape", "ndim", "dtype", "name", "size",
                   "stop_gradient", "place", "is_bias", "training"}
# calls whose result is never traced regardless of arguments
_UNTAINT_CALLS = {"isinstance", "len", "hasattr", "type", "callable",
                  "id", "repr", "str", "getattr_static", "issubclass"}
_JIT_DECORATORS = {"jit", "to_static", "pjit", "checkpoint", "remat",
                   "grad", "value_and_grad", "vmap", "pmap", "scan"}


def _last_name(node: ast.AST) -> Optional[str]:
    """Trailing identifier of a dotted/called decorator expression."""
    if isinstance(node, ast.Call):
        return _last_name(node.func)
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_layer_class(cls: ast.ClassDef) -> bool:
    return any((_last_name(b) or "").endswith("Layer") or
               (_last_name(b) or "").endswith("Module")
               for b in cls.bases)


def _known_fault_points() -> Set[str]:
    try:
        from paddle_tpu.framework.chaos import known_fault_points
        return set(known_fault_points())
    except Exception:                  # noqa: BLE001 — linter must not die
        return set()


class _Taint:
    """Expression-level taint evaluator over a set of traced names."""

    def __init__(self, tainted: Set[str]):
        self.tainted = tainted

    def __call__(self, node: Optional[ast.AST]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _METADATA_ATTRS:
                return False
            return self(node.value)
        if isinstance(node, ast.Call):
            fname = _last_name(node.func)
            if fname in _UNTAINT_CALLS:
                return False
            if any(self(a) for a in node.args) or \
                    any(self(k.value) for k in node.keywords):
                return True
            return self(node.func)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return self(node.left) or any(self(c)
                                          for c in node.comparators)
        if isinstance(node, ast.Subscript):
            return self(node.value) or self(node.slice)
        if isinstance(node, (ast.Constant, ast.Lambda)):
            return False
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return False
        return any(self(c) for c in ast.iter_child_nodes(node))


class _FileLinter:
    def __init__(self, source: str, filename: str):
        self.source = source
        self.filename = filename
        self.sup = parse_suppressions(source)
        self.report = Report()
        self.np_aliases: Set[str] = set()
        self.registered_points: Set[str] = set()
        self.tuple_names: Set[str] = set()
        self._last_value: Optional[ast.AST] = None

    # -- emission ---------------------------------------------------------
    def emit(self, rule: str, node: ast.AST, message: str,
             severity: Severity, hint: Optional[str] = None):
        line = getattr(node, "lineno", None)
        # header-span suppression: pragmas on a decorator line or any
        # line of a multi-line statement header count (allows_node)
        if not self.sup.allows_node(rule, node):
            return
        self.report.add(Diagnostic(
            rule, message, severity, file=self.filename, line=line,
            col=getattr(node, "col_offset", None), hint=hint))

    # -- driver -----------------------------------------------------------
    def run(self) -> Report:
        try:
            tree = ast.parse(self.source, filename=self.filename)
        except SyntaxError as e:
            self.report.add(Diagnostic(
                "PTA201", f"file does not parse: {e}", Severity.ERROR,
                file=self.filename, line=e.lineno))
            return self.report
        self._collect_imports(tree)
        self._lint_chaos(tree)
        for fn, cls, inherited in self._jit_scope_functions(tree):
            self._lint_jit_scope(fn, cls, inherited)
        self.report.files_seen.append(self.filename)
        return self.report

    def _collect_imports(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "numpy":
                        self.np_aliases.add(a.asname or "numpy")
            elif isinstance(node, ast.Call) and \
                    _last_name(node.func) == "register_fault_point":
                if node.args and isinstance(node.args[0], ast.Constant):
                    self.registered_points.add(str(node.args[0].value))

    # -- jit-scope discovery ----------------------------------------------
    def _jit_scope_functions(self, tree):
        """(fn, enclosing_class, inherited_taint) triples for every
        function assumed to run under a trace."""
        out = []

        def is_jit(fn: ast.AST, cls) -> bool:
            for d in fn.decorator_list:
                # @not_to_static is the machine-readable eager-only
                # contract (jit.not_to_static): dy2static skips the
                # function, so the jit-scope rules must not apply
                if (_last_name(d) or "") == "not_to_static":
                    return False
            for d in fn.decorator_list:
                if (_last_name(d) or "") in _JIT_DECORATORS:
                    return True
            return cls is not None and fn.name == "forward"

        def walk(node, cls, in_scope, outer_taint):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child if _is_layer_class(child) else None,
                         False, set())
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    scoped = in_scope or is_jit(child, cls)
                    if scoped:
                        out.append((child, cls, set(outer_taint)))
                    # nested defs inherit the enclosing traced locals
                    walk(child, None, scoped,
                         outer_taint | self._param_names(child)
                         if scoped else set())
                else:
                    walk(child, cls, in_scope, outer_taint)

        walk(tree, None, False, set())
        # report each function once, outermost scope wins
        seen, uniq = set(), []
        for fn, cls, taint in out:
            if id(fn) not in seen:
                seen.add(id(fn))
                uniq.append((fn, cls, taint))
        return uniq

    @staticmethod
    def _param_names(fn) -> Set[str]:
        args = fn.args
        names = {a.arg for a in
                 args.posonlyargs + args.args + args.kwonlyargs}
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
        names.discard("self")
        names.discard("cls")
        return names

    # -- the jit-scope lint -------------------------------------------------
    def _lint_jit_scope(self, fn, cls, inherited: Set[str]):
        tainted = self._param_names(fn) | inherited
        # locals = params + every name the body writes (dy2static's
        # collector, so both tools see the same binding set)
        coll = _NameCollector()
        for s in fn.body:
            coll.visit(s)
        local_names = set(coll.writes) | self._param_names(fn)
        declared_nonlocal: Set[str] = set()
        # *args/**kwargs are tuples/dicts of traced values: elements are
        # traced, but bare truthiness (`if rest:`) is a static len check
        self.tuple_names: Set[str] = set()
        if fn.args.vararg:
            self.tuple_names.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            self.tuple_names.add(fn.args.kwarg.arg)
        # pass 1: propagate taint to fixpoint (two sweeps reach it for
        # straight-line + single-loop dataflow), no reporting
        for _ in range(2):
            self._sweep(fn.body, tainted, declared_nonlocal,
                        local_names, report=False)
        self._sweep(fn.body, tainted, declared_nonlocal, local_names,
                    report=True)

    def _sweep(self, stmts: Sequence[ast.stmt], tainted: Set[str],
               declared_nonlocal: Set[str], local_names: Set[str],
               report: bool):
        taint = _Taint(tainted)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue               # visited as its own jit scope
            if isinstance(stmt, (ast.Global, ast.Nonlocal)):
                declared_nonlocal.update(stmt.names)
                if report:
                    self.emit(
                        "PTA203", stmt,
                        f"`{type(stmt).__name__.lower()} "
                        f"{', '.join(stmt.names)}` inside a jit-scope "
                        "function — writes escape the trace and run "
                        "once, at trace time", Severity.WARNING,
                        hint="return the value instead of writing "
                             "enclosing scope")
                continue
            if isinstance(stmt, ast.If):
                if report:
                    self._check_numpy_calls(stmt.test, taint)
                if report and taint(stmt.test) and \
                        not self._static_truthy(stmt.test):
                    self._emit_branch("PTA201", stmt, "if")
                self._sweep(stmt.body, tainted, declared_nonlocal,
                            local_names, report)
                self._sweep(stmt.orelse, tainted, declared_nonlocal,
                            local_names, report)
                continue
            if isinstance(stmt, ast.While):
                if report:
                    self._check_numpy_calls(stmt.test, taint)
                if report and taint(stmt.test) and \
                        not self._static_truthy(stmt.test):
                    self._emit_branch("PTA202", stmt, "while")
                self._sweep(stmt.body, tainted, declared_nonlocal,
                            local_names, report)
                self._sweep(stmt.orelse, tainted, declared_nonlocal,
                            local_names, report)
                continue
            if isinstance(stmt, ast.For):
                if report:
                    self._check_numpy_calls(stmt.iter, taint)
                if report and taint(stmt.iter) and \
                        not self._static_truthy(stmt.iter):
                    self._emit_branch("PTA202", stmt, "for")
                if taint(stmt.iter) and isinstance(stmt.target, ast.Name):
                    tainted.add(stmt.target.id)
                self._sweep(stmt.body, tainted, declared_nonlocal,
                            local_names, report)
                self._sweep(stmt.orelse, tainted, declared_nonlocal,
                            local_names, report)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._sweep(stmt.body, tainted, declared_nonlocal,
                            local_names, report)
                continue
            if isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._sweep(blk, tainted, declared_nonlocal,
                                local_names, report)
                for h in stmt.handlers:
                    self._sweep(h.body, tainted, declared_nonlocal,
                                local_names, report)
                continue
            # straight-line statement: stores + expression checks
            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                value = stmt.value
                val_tainted = taint(value)
                if isinstance(stmt, ast.AugAssign) and \
                        isinstance(stmt.target, ast.Name):
                    # x += clean keeps x traced if it already was
                    val_tainted = val_tainted or \
                        stmt.target.id in tainted
                self._last_value = value
                targets = (stmt.targets
                           if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    self._check_store(t, val_tainted, tainted,
                                      declared_nonlocal, local_names,
                                      report)
            if report:
                self._check_numpy_calls(stmt, taint)
                self._check_print(stmt, taint)

    def _static_truthy(self, test: ast.AST) -> bool:
        """True when a tainted test is nonetheless static under a trace:
        bare truthiness of a *args/**kwargs container (or a slice of
        one) is a length check, not a tensor-bool."""
        return isinstance(test, ast.Name) and test.id in self.tuple_names

    def _is_tuple_expr(self, value: Optional[ast.AST]) -> bool:
        """Does ``value`` evaluate to a tuple even when its elements are
        traced?  Tuple/list displays, and slices of names already known
        to be tuples (``states = flat[4:]``)."""
        if isinstance(value, (ast.Tuple, ast.List)):
            return True
        return (isinstance(value, ast.Subscript)
                and isinstance(value.slice, ast.Slice)
                and isinstance(value.value, ast.Name)
                and value.value.id in self.tuple_names)

    def _emit_branch(self, rule: str, stmt, kw: str):
        body = list(stmt.body) + list(getattr(stmt, "orelse", []))
        convertible = not _escapes(body)
        what = ("a traced value decides a Python-level branch"
                if rule == "PTA201" else
                "a traced value bounds a Python-level loop")
        if convertible:
            self.emit(
                rule, stmt,
                f"`{kw}` on a traced value — {what}; dy2static can "
                "outline this statement, but only under to_static "
                "capture", Severity.WARNING,
                hint="use paddle_tpu.static.nn.cond/while_loop "
                     "explicitly, or confirm the callable is traced "
                     "via jit.to_static (which rewrites it)")
        else:
            self.emit(
                rule, stmt,
                f"`{kw}` on a traced value with a body dy2static "
                "cannot outline (return/break/attribute store inside) "
                "— under a trace this crashes on tracer-bool or bakes "
                "one branch", Severity.ERROR,
                hint="rewrite with static.nn.cond / lax.select on "
                     "values, or hoist the branch out of the traced "
                     "function")

    def _check_store(self, target, val_tainted: bool, tainted: Set[str],
                     declared_nonlocal: Set[str], local_names: Set[str],
                     report: bool):
        if isinstance(target, (ast.Tuple, ast.List)):
            # unpacking: each element receives ONE value from the RHS,
            # not the RHS itself — pair element-wise when the RHS is a
            # matching display, otherwise the element value is unknown
            # (an unpacked tensor must NOT inherit the tuple-ness of
            # the container it came from)
            rhs = self._last_value
            elts = (rhs.elts if isinstance(rhs, (ast.Tuple, ast.List))
                    and len(rhs.elts) == len(target.elts) else None)
            for i, e in enumerate(target.elts):
                self._last_value = elts[i] if elts else None
                self._check_store(e, val_tainted, tainted,
                                  declared_nonlocal, local_names, report)
            self._last_value = rhs
            return
        if isinstance(target, ast.Name):
            if val_tainted:
                tainted.add(target.id)
            else:
                tainted.discard(target.id)
            if self._is_tuple_expr(self._last_value):
                self.tuple_names.add(target.id)
            else:
                self.tuple_names.discard(target.id)
            if report and val_tainted and target.id in declared_nonlocal:
                self.emit(
                    "PTA204", target,
                    f"traced value leaks through "
                    f"`{target.id}` into an enclosing scope — it "
                    "outlives the trace as a dead tracer",
                    Severity.WARNING,
                    hint="return it from the traced function instead")
            return
        if not report:
            return
        base = target
        while isinstance(base, (ast.Attribute, ast.Subscript)):
            base = base.value
        base_name = base.id if isinstance(base, ast.Name) else None
        on_self = base_name == "self"
        nonlocal_store = base_name is not None and \
            base_name not in local_names and not on_self
        if isinstance(target, (ast.Attribute, ast.Subscript)):
            if val_tainted and (on_self or nonlocal_store):
                where = "self" if on_self else f"`{base_name}`"
                self.emit(
                    "PTA204", target,
                    f"traced value stored into {where} — the tracer "
                    "leaks out of the compiled scope and later eager "
                    "reads see a stale/invalid tracer",
                    Severity.WARNING,
                    hint="register_buffer for per-step state (buffers "
                         "thread through capture), or return the value")
            elif on_self:
                self.emit(
                    "PTA203", target,
                    "attribute store on self inside a jit-scope "
                    "function — the mutation happens at trace time "
                    "only, NOT per call", Severity.WARNING,
                    hint="mutate in __init__/eager code, or use a "
                         "registered buffer")
            elif nonlocal_store and val_tainted is False and \
                    base_name is not None:
                self.emit(
                    "PTA203", target,
                    f"store into non-local `{base_name}` inside a "
                    "jit-scope function — runs once at trace time",
                    Severity.WARNING,
                    hint="keep trace-time code pure; do bookkeeping "
                         "outside the traced callable")

    def _check_numpy_calls(self, stmt, taint: _Taint):
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            root = func
            while isinstance(root, ast.Attribute):
                root = root.value
            is_np = isinstance(root, ast.Name) and \
                root.id in self.np_aliases and \
                isinstance(func, ast.Attribute)
            if is_np and (any(taint(a) for a in node.args) or
                          any(taint(k.value) for k in node.keywords)):
                self.emit(
                    "PTA205", node,
                    f"numpy call `{ast.unparse(func)}` on a traced "
                    "array — under jit this either concretizes (host "
                    "sync + constant-folds the tracer) or raises "
                    "TracerArrayConversionError", Severity.ERROR,
                    hint="use the jnp/paddle_tpu equivalent, or move "
                         "the numpy code outside the traced function")

    def _check_print(self, stmt, taint: _Taint):
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "print":
                self.emit(
                    "PTA203", node,
                    "print() inside a jit-scope function — fires at "
                    "trace time only (or not at all once cached)",
                    Severity.WARNING,
                    hint="use jax.debug.print for per-execution output "
                         "(and see PTA103 for its cost)")

    # -- chaos fault-point hygiene (PTA301/302) -----------------------------
    def _lint_chaos(self, tree):
        known = _known_fault_points()
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and
                    _last_name(node.func) == "fault_point"):
                continue
            pt_name = None
            if node.args and isinstance(node.args[0], ast.Constant):
                pt_name = str(node.args[0].value)
            if pt_name is not None and known and \
                    pt_name not in known | self.registered_points:
                self.emit(
                    "PTA302", node,
                    f"fault point {pt_name!r} is not declared in the "
                    "chaos registry — arming it raises, and a typo'd "
                    "spec would inject nothing (false-green chaos run)",
                    Severity.ERROR,
                    hint="use a registered point or call "
                         "chaos.register_fault_point first; known: "
                         + ", ".join(sorted(known)))
            guarded = False
            cur = node
            while id(cur) in parents:
                cur = parents[id(cur)]
                if isinstance(cur, ast.Try):
                    guarded = True
                    break
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    break
            if not guarded:
                self.emit(
                    "PTA301", node,
                    f"chaos fault point {pt_name or '<dynamic>'!r} "
                    "fired with no try/retry guard in the enclosing "
                    "function — an armed run escalates the injected "
                    "fault into a crash here", Severity.WARNING,
                    hint="wrap in retry/backoff (PsClient pattern) or, "
                         "if a caller owns recovery, note it with "
                         "`# pta: disable=PTA301 (<who retries>)`")


def lint_source(source: str, filename: str = "<string>",
                disable: Sequence[str] = ()) -> Report:
    """AST-lint one source string."""
    return _FileLinter(source, filename).run().filter(disable=disable)


def lint_file(path: str, disable: Sequence[str] = ()) -> Report:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, filename=path, disable=disable)
