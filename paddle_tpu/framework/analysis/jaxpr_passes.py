"""Jaxpr-level IR passes — the post-trace half of the program analyzer.

The reference validates and rewrites ProgramDescs through graph passes
(paddle/fluid/framework/ir + inference/analysis) before the executor
runs them; here the traced IR is a jaxpr, so the passes run over
``jax.make_jaxpr`` output instead of an SSA graph of OpDescs.  Each pass
reads the closed jaxpr (plus trace metadata: input labels, donation) and
emits :class:`~.diagnostics.Diagnostic` records; nothing is rewritten —
XLA owns optimization, the analyzer owns *explaining the trace to the
human* before a TPU hour is spent on it.

Shipped passes (stable IDs, see diagnostics.RULES):

========  ==============================================================
PTA101    silent dtype upcasts: mixed-width float promotion inside an
          eqn, and any f64/c128 value appearing in the program
PTA102    dead equations and unused inputs (params that never reach an
          output — the trace equivalent of unused-var warnings)
PTA103    host callbacks / syncs inside the traced program
          (debug_callback, io_callback, pure_callback)
PTA104    donated-buffer misuse: a donated input whose shape/dtype
          matches no output can never be reused (XLA warns at runtime;
          this catches it pre-dispatch), and large aliasable
          inputs that are NOT donated
PTA105    dispatch-cache defeaters baked in as constants: large arrays
          closed over instead of passed in, frozen rng keys, weak-typed
          scalar closures that retrace on every new Python value
PTA106    per-eqn FLOP/byte estimates with a top-k heaviest-ops report
========  ==============================================================

The distributed-semantics family (PTA501-506, collectives.py) runs as
part of :func:`analyze_jaxpr` too — free on ordinary jit programs, and
the cost pass is shard_map-aware: inside a manual region shapes are
already per-device, higher-order wrapper eqns (pjit/shard_map) are not
double-counted, and collective eqns are tagged with ANALYTIC wire bytes
(``distributed/wire.py::wire_nbytes`` on the payload encoding, scaled
by the ring/gather traffic factor for the mesh axis size) instead of
host-memory-moved estimates — so ``perf_report attribute`` stops
over-counting sharded programs.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.framework.analysis.collectives import (
    COLLECTIVE_PRIMS, run_collective_passes)
from paddle_tpu.framework.analysis.diagnostics import (
    Diagnostic, Report, Severity, register_rule)

__all__ = ["analyze_jaxpr", "analyze_callable", "analyze_model",
           "iter_eqns", "eqn_cost"]

register_rule("PTA101", "silent dtype upcast", Severity.WARNING, "jaxpr")
register_rule("PTA102", "dead equation / unused input", Severity.WARNING,
              "jaxpr")
register_rule("PTA103", "host callback inside jit", Severity.WARNING,
              "jaxpr")
register_rule("PTA104", "donated-buffer misuse", Severity.WARNING, "jaxpr")
register_rule("PTA105", "dispatch-cache defeating constant",
              Severity.WARNING, "jaxpr")
register_rule("PTA106", "op cost report", Severity.INFO, "jaxpr")

# consts at or above this many elements should be inputs, not closures
_LARGE_CONST_ELEMS = 4096
# un-donated aliasable inputs at or above this many bytes get the
# donation hint (below it the saved HBM is noise)
_DONATION_HINT_BYTES = 1 << 20

_CALLBACK_PRIMS = {"debug_callback", "io_callback", "pure_callback",
                   "callback", "outside_call", "host_callback_call"}

# eqn.params values holding nested jaxprs, by primitive
_SUBJAXPR_KEYS = ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr",
                  "branches", "fun_jaxpr")


def _float_width(dt) -> Optional[int]:
    try:
        dt = np.dtype(dt)
    except TypeError:                  # extended dtypes (prng keys) / tokens
        return None
    if dt.kind in ("f", "c"):
        return dt.itemsize
    return None


def _np_dtype(aval):
    try:
        return np.dtype(getattr(aval, "dtype", None))
    except TypeError:
        return None


def _aval(v):
    import jax
    if hasattr(v, "aval"):
        return v.aval
    return jax.core.get_aval(v.val if hasattr(v, "val") else v)


def _nbytes(aval) -> int:
    try:
        return int(np.prod(aval.shape, dtype=np.int64)) * \
            np.dtype(aval.dtype).itemsize
    except Exception:                  # noqa: BLE001 — abstract tokens etc.
        return 0


def _subjaxprs(eqn):
    """Nested jaxprs of a higher-order eqn (pjit, scan, while, cond,
    custom_*), normalized to plain Jaxpr objects."""
    out = []
    for k in _SUBJAXPR_KEYS:
        v = eqn.params.get(k)
        if v is None:
            continue
        vs = v if isinstance(v, (list, tuple)) else [v]
        for j in vs:
            j = getattr(j, "jaxpr", j)     # ClosedJaxpr -> Jaxpr
            if hasattr(j, "eqns"):
                out.append(j)
    return out


def iter_eqns(jaxpr, depth: int = 0):
    """Yield ``(eqn, depth)`` over the jaxpr and every nested sub-jaxpr
    (scan/while/cond bodies, pjit-inlined calls)."""
    for eqn in jaxpr.eqns:
        yield eqn, depth
        for sub in _subjaxprs(eqn):
            yield from iter_eqns(sub, depth + 1)


# ---------------------------------------------------------------------------
# cost model (PTA106)
# ---------------------------------------------------------------------------


def eqn_cost(eqn) -> Tuple[int, int]:
    """(flops, bytes) estimate for one eqn.  Deliberately coarse — the
    point is ranking ops inside one program, not absolute roofline math
    (compare the reference's per-op benchmark configs, which measure
    instead of estimating)."""
    name = eqn.primitive.name
    out_elems = sum(int(np.prod(_aval(o).shape, dtype=np.int64))
                    for o in eqn.outvars)
    moved = sum(_nbytes(_aval(v)) for v in
                list(eqn.invars) + list(eqn.outvars))
    if name == "dot_general":
        dn = eqn.params["dimension_numbers"]
        (lhs_c, _), _ = dn
        lhs = _aval(eqn.invars[0]).shape
        k = int(np.prod([lhs[i] for i in lhs_c], dtype=np.int64)) or 1
        return 2 * out_elems * k, moved
    if name == "conv_general_dilated":
        rhs = _aval(eqn.invars[1]).shape
        dn = eqn.params.get("dimension_numbers")
        spatial_and_in = [d for i, d in enumerate(rhs)
                          if dn is None or i != dn.rhs_spec[0]]
        per_out = int(np.prod(spatial_and_in, dtype=np.int64)) or 1
        feature_group = int(eqn.params.get("feature_group_count", 1)) or 1
        return 2 * out_elems * per_out // feature_group, moved
    if name.startswith("reduce_") or name in ("argmax", "argmin"):
        in_elems = sum(int(np.prod(_aval(v).shape, dtype=np.int64))
                       for v in eqn.invars)
        return in_elems, moved
    return out_elems, moved


# ---------------------------------------------------------------------------
# the passes
# ---------------------------------------------------------------------------


def _pass_dtype(jaxpr, consts, name, report: Report):
    x64 = {np.dtype(d) for d in ("float64", "complex128")}
    for c in consts:
        dt = _np_dtype(c)
        if dt in x64:
            report.add(Diagnostic(
                "PTA101", f"{name}: float64 constant closed over the "
                f"traced program (shape {tuple(np.shape(c))}) — on TPU "
                "this silently widens every consumer and falls off the "
                "fast path", Severity.ERROR,
                hint="build the constant with an explicit float32/"
                     "bfloat16 dtype, or disable jax_enable_x64"))
    in_f64 = any(_float_width(_aval(v).dtype) == 8
                 for v in jaxpr.invars
                 if _float_width(_aval(v).dtype) is not None)
    for eqn, depth in iter_eqns(jaxpr):
        widths = {}
        for v in eqn.invars:
            w = _float_width(_aval(v).dtype)
            if w is not None:
                widths.setdefault(w, str(np.dtype(_aval(v).dtype)))
        out_w = [(_float_width(_aval(o).dtype), _aval(o).dtype)
                 for o in eqn.outvars]
        if len(widths) > 1 and eqn.primitive.name != \
                "convert_element_type":
            widest = max(widths)
            if any(w == widest for w, _ in out_w if w is not None):
                report.add(Diagnostic(
                    "PTA101",
                    f"{name}: {eqn.primitive.name} mixes float widths "
                    f"({', '.join(sorted(widths.values()))}) — the "
                    f"result is silently promoted to {widths[widest]}",
                    Severity.WARNING,
                    hint="cast the narrow operand explicitly, or keep "
                         "both sides in the compute dtype (bf16 under "
                         "amp) so the MXU path is not lost"))
        if not in_f64:
            for w, dt in out_w:
                if w == 8:
                    report.add(Diagnostic(
                        "PTA101",
                        f"{name}: {eqn.primitive.name} produces "
                        f"{np.dtype(dt)} with no float64 program input "
                        "— an accidental x64 upcast",
                        Severity.ERROR,
                        hint="trace the source constant/op and pin its "
                             "dtype to float32"))
                    break


def _pass_dead_code(jaxpr, name, invar_labels, report: Report):
    import jax
    live = {v for v in jaxpr.outvars
            if not isinstance(v, jax.core.Literal)}
    for eqn in reversed(jaxpr.eqns):
        out_live = any(o in live for o in eqn.outvars)
        if out_live or eqn.effects:
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    live.add(v)
        elif eqn.primitive.name in ("broadcast_in_dim", "iota") and \
                all(isinstance(v, jax.core.Literal) for v in eqn.invars):
            # a dead LITERAL materialization is free: jax's own vjp
            # rules leave these behind (e.g. relu's custom_jvp zeros)
            # and XLA constant-folds them — flagging would teach users
            # to ignore PTA102
            continue
        else:
            report.add(Diagnostic(
                "PTA102",
                f"{name}: dead equation `{eqn.primitive.name}` — its "
                "outputs are never used by any program output",
                Severity.WARNING,
                hint="drop the computation, or return its result; XLA "
                     "DCEs it, but the trace (and every retrace) still "
                     "pays for it"))
    for i, v in enumerate(jaxpr.invars):
        if v not in live:
            label = invar_labels[i] if invar_labels and \
                i < len(invar_labels) else f"input[{i}]"
            if label == "rng_key":
                # the capture protocol threads a key into every trace;
                # an eval-mode model legitimately ignores it
                continue
            report.add(Diagnostic(
                "PTA102",
                f"{name}: input `{label}` never reaches any output "
                "(dead parameter)",
                Severity.WARNING,
                hint="remove the input, or check the forward actually "
                     "uses the layer it belongs to"))


def _pass_callbacks(jaxpr, name, report: Report):
    for eqn, depth in iter_eqns(jaxpr):
        pname = eqn.primitive.name
        if pname in _CALLBACK_PRIMS or "callback" in pname:
            cb = eqn.params.get("callback")
            what = getattr(cb, "__name__", None) or pname
            report.add(Diagnostic(
                "PTA103",
                f"{name}: host callback `{pname}` ({what}) inside the "
                "traced program — every execution round-trips to the "
                "host, serializing the device stream",
                Severity.WARNING,
                hint="strip jax.debug.print/io_callback from production "
                     "traces, or gate them behind a debug flag"))


def _pass_donation(jaxpr, name, donate_argnums, invar_labels,
                   report: Report):
    out_avals = [(tuple(getattr(_aval(o), "shape", ())), _np_dtype(_aval(o)))
                 for o in jaxpr.outvars if _np_dtype(_aval(o)) is not None]
    pool = list(out_avals)
    donated = set(donate_argnums or ())
    for i in sorted(donated):
        if i >= len(jaxpr.invars):
            continue
        a = _aval(jaxpr.invars[i])
        if _np_dtype(a) is None:
            continue
        key = (tuple(a.shape), _np_dtype(a))
        label = invar_labels[i] if invar_labels and \
            i < len(invar_labels) else f"input[{i}]"
        if key in pool:
            pool.remove(key)          # each output aliases one buffer
        else:
            report.add(Diagnostic(
                "PTA104",
                f"{name}: donated input `{label}` "
                f"{key[1]}{list(key[0])} matches no output — the "
                "buffer is freed but never reused, and any later use "
                "of the live Tensor hits a deleted array",
                Severity.WARNING,
                hint="donate only buffers the step returns updated "
                     "(params/opt states), or drop it from "
                     "donate_argnums"))
    if donate_argnums is not None:
        pool = list(out_avals)
        for i, v in enumerate(jaxpr.invars):
            if i in donated:
                continue
            a = _aval(v)
            if _np_dtype(a) is None:
                continue
            key = (tuple(a.shape), _np_dtype(a))
            if key in pool and _nbytes(a) >= _DONATION_HINT_BYTES:
                pool.remove(key)
                label = invar_labels[i] if invar_labels and \
                    i < len(invar_labels) else f"input[{i}]"
                report.add(Diagnostic(
                    "PTA104",
                    f"{name}: input `{label}` ({_nbytes(a) >> 20} MiB) "
                    "shape-matches an output but is not donated — HBM "
                    "holds two live copies across the step",
                    Severity.INFO,
                    hint="add it to donate_argnums if the caller never "
                         "reads the pre-step value"))


def _pass_consts(jaxpr, consts, name, report: Report):
    import jax
    for c in consts:
        arr = np.asarray(c) if not hasattr(c, "dtype") else c
        shape = tuple(getattr(arr, "shape", ()))
        elems = int(np.prod(shape, dtype=np.int64)) if shape else 1
        dt = _np_dtype(arr)
        if dt is None:                 # prng key const: frozen randomness
            report.add(Diagnostic(
                "PTA105",
                f"{name}: typed rng key baked in as a constant — every "
                "call replays identical randomness",
                Severity.WARNING,
                hint="take the key as an argument (see "
                     "jit._GeneratorKeyGuard: keys are traced inputs)"))
            continue
        if elems >= _LARGE_CONST_ELEMS:
            kib = elems * dt.itemsize >> 10
            report.add(Diagnostic(
                "PTA105",
                f"{name}: large constant ({dt}{list(shape)}, {kib} KiB) "
                "baked into the traced program — it is re-hashed on "
                "every dispatch-cache probe and re-staged per "
                "executable", Severity.WARNING,
                hint="pass it as an argument (params/buffers thread "
                     "through capture) instead of closing over it"))
            continue
        if dt == np.uint32 and shape and shape[-1] == 2:
            report.add(Diagnostic(
                "PTA105",
                f"{name}: rng key baked in as a constant — every call "
                "replays identical randomness, and threading a fresh "
                "key instead forces a retrace per step",
                Severity.WARNING,
                hint="take the key as an argument (see "
                     "jit._GeneratorKeyGuard: keys are traced inputs)"))
            continue
        try:
            weak = jax.core.get_aval(c).weak_type
        except Exception:              # noqa: BLE001
            weak = False
        if weak and elems == 1:
            report.add(Diagnostic(
                "PTA105",
                f"{name}: weak-typed Python scalar ({dt}) closed over "
                "the trace — each distinct value is a fresh cache "
                "entry (silent recompilation)",
                Severity.WARNING,
                hint="pass it as a jnp array argument, or mark it "
                     "static if it is genuinely a config constant"))


# ring/gather traffic per replica as a multiple of the local payload
# bytes, by collective family (k = mesh axis size): a psum is a ring
# all-reduce (2(k-1)/k), all_gather pulls every peer's shard (k-1),
# reduce-scatter/all-to-all move (k-1)/k, ppermute one full payload
def _collective_traffic_factor(pname: str, k: int) -> float:
    if k <= 1:
        return 0.0
    if pname in ("psum", "pmax", "pmin"):
        return 2.0 * (k - 1) / k
    if pname == "all_gather":
        return float(k - 1)
    if pname in ("psum_scatter", "reduce_scatter", "all_to_all"):
        return (k - 1) / k
    return 1.0                        # ppermute / pbroadcast


_WIRE_OF_DTYPE = {"float32": "f32", "bfloat16": "bf16",
                  "float16": "f16", "int8": "int8", "uint8": "int8"}


def _collective_wire_bytes(eqn, axis_sizes: Dict[str, int]) -> int:
    """Analytic per-replica wire bytes of one collective eqn — the
    payload encoded per ``distributed/wire.py::wire_nbytes``, scaled by
    the traffic factor for the collective family and axis size."""
    from paddle_tpu.distributed.wire import wire_nbytes
    from paddle_tpu.framework.analysis.collectives import _collective_axes
    k = 1
    for a in _collective_axes(eqn):
        k *= int(axis_sizes.get(a, 1) or 1)
    factor = _collective_traffic_factor(eqn.primitive.name, k)
    total = 0.0
    for v in eqn.invars:
        aval = _aval(v)
        dt = _np_dtype(aval)
        if dt is None:
            continue
        elems = int(np.prod(getattr(aval, "shape", ()), dtype=np.int64))
        wire = _WIRE_OF_DTYPE.get(dt.name)
        if wire is None:              # wider ints/floats account as f32
            total += float(elems * dt.itemsize) * factor
        else:
            total += float(wire_nbytes(elems, wire)) * factor
    return int(total)


def _pass_cost(jaxpr, name, top_k, report: Report):
    rows: List[Tuple[int, int, str]] = []
    total_f = total_b = coll_b = 0
    by_op: dict = {}
    state = {"manual": False}

    def note(pname, f, b, n=1):
        nonlocal total_f, total_b
        total_f += f
        total_b += b
        rows.append((f, b, pname))
        agg = by_op.setdefault(pname, [0, 0, 0])
        agg[0] += f
        agg[1] += b
        agg[2] += n

    def walk(jx, axis_sizes, trips=1):
        nonlocal coll_b
        for eqn in jx.eqns:
            pname = eqn.primitive.name
            subs = _subjaxprs(eqn)
            if pname == "shard_map":
                # manual region: body shapes are already PER-DEVICE —
                # count only the body, under the region's mesh sizes
                state["manual"] = True
                mesh = eqn.params.get("mesh")
                try:
                    sizes = {a: int(s) for a, s in
                             dict(getattr(mesh, "shape", {})).items()}
                except TypeError:
                    sizes = axis_sizes
                for sub in subs:
                    walk(sub, sizes, trips)
                continue
            if pname in COLLECTIVE_PRIMS:
                b = _collective_wire_bytes(eqn, axis_sizes) * trips
                coll_b += b
                note(pname, 0, b, trips)
                continue
            if subs:
                # higher-order wrapper (pjit/scan/cond/custom_*): its
                # cost IS its bodies' — counting the wrapper's global
                # outputs too is exactly the sharded-program over-count.
                # A scan body runs `length` times, so its costs (and
                # the ring collectives inside it) multiply by the trip
                # count — the fused-ring wire bytes would otherwise
                # read as one hop
                t = trips * max(1, int(eqn.params.get("length", 1) or 1)) \
                    if pname == "scan" else trips
                for sub in subs:
                    walk(sub, axis_sizes, t)
                continue
            f, b = eqn_cost(eqn)
            note(pname, f * trips, b * trips, trips)

    walk(jaxpr, {})
    # structured twin of the PTA106 diagnostics: per-primitive
    # aggregates the span<->cost join (tools/perf_report.py attribute)
    # consumes without parsing message strings.  per_device=True marks
    # totals counted inside manual regions (shard-local shapes);
    # collective rows carry analytic wire bytes, not FLOPs
    report.cost = {
        "name": name,
        "total_flops": int(total_f),
        "total_bytes": int(total_b),
        "n_eqns": len(rows),
        "per_device": bool(state["manual"]),
        "collective_wire_bytes": int(coll_b),
        "by_op": [{"op": op, "flops": int(f), "bytes": int(b),
                   "count": int(c)}
                  for op, (f, b, c) in sorted(
                      by_op.items(), key=lambda kv: -kv[1][0])],
    }
    rows.sort(key=lambda r: -r[0])
    for rank, (f, b, pname) in enumerate(rows[:top_k], start=1):
        if f == 0:
            break
        share = f / total_f if total_f else 0.0
        report.add(Diagnostic(
            "PTA106",
            f"{name}: #{rank} heaviest op `{pname}` ≈ {f:,} flops "
            f"({share:.0%} of program), {b >> 10} KiB moved",
            Severity.INFO))
    report.add(Diagnostic(
        "PTA106",
        f"{name}: program total ≈ {total_f:,} flops, "
        f"{total_b >> 20} MiB moved across {len(rows)} eqns "
        f"(arithmetic intensity {total_f / total_b if total_b else 0:.1f} "
        "flop/byte)", Severity.INFO))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def analyze_jaxpr(closed_jaxpr, name: str = "<traced>",
                  donate_argnums: Optional[Sequence[int]] = None,
                  invar_labels: Optional[Sequence[str]] = None,
                  outvar_labels: Optional[Sequence[str]] = None,
                  top_k: int = 5, disable: Sequence[str] = (),
                  with_cost: bool = True) -> Report:
    """Run every jaxpr pass over a ``jax.make_jaxpr`` result —
    the PTA1xx family plus the distributed-semantics PTA5xx passes
    (collectives.py; no-ops on programs without shard_map regions).
    ``outvar_labels`` name the program outputs so a PTA501 finding can
    say WHICH leaf escapes unreduced."""
    jaxpr = closed_jaxpr.jaxpr
    consts = list(closed_jaxpr.consts)
    report = Report()
    _pass_dtype(jaxpr, consts, name, report)
    _pass_dead_code(jaxpr, name, invar_labels, report)
    _pass_callbacks(jaxpr, name, report)
    _pass_donation(jaxpr, name, donate_argnums, invar_labels, report)
    _pass_consts(jaxpr, consts, name, report)
    run_collective_passes(closed_jaxpr, name, report,
                          donate_argnums=donate_argnums,
                          invar_labels=invar_labels,
                          outvar_labels=outvar_labels)
    if with_cost:
        _pass_cost(jaxpr, name, top_k, report)
    return report.filter(disable=disable)


def _to_aval(x):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core import Tensor
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    if isinstance(x, Tensor):
        return jax.ShapeDtypeStruct(tuple(x.shape), jnp.dtype(x.dtype))
    arr = jnp.asarray(x)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def analyze_callable(fn: Callable, *example_args,
                     donate_argnums: Sequence[int] = (),
                     tensors: bool = False, name: Optional[str] = None,
                     **analyze_kwargs) -> Report:
    """Trace ``fn`` on aval stand-ins of ``example_args`` and analyze the
    jaxpr.  ``tensors=True`` wraps array arguments in paddle Tensors
    before the call (for paddle-level functions); plain jax functions
    take arrays directly.  Tracing is abstract — no FLOP is spent."""
    import jax
    from paddle_tpu.core import Tensor
    avals = [_to_aval(a) for a in example_args]
    if tensors:
        def wrapped(*arrs):
            out = fn(*[Tensor(a) for a in arrs])
            leaves = jax.tree_util.tree_leaves(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            return tuple(o._data if isinstance(o, Tensor) else o
                         for o in leaves)
        target = wrapped
    else:
        target = fn
    closed = jax.make_jaxpr(target)(*avals)
    return analyze_jaxpr(
        closed, name=name or getattr(fn, "__name__", "<callable>"),
        donate_argnums=donate_argnums, **analyze_kwargs)


def analyze_model(model, *example_inputs, name: Optional[str] = None,
                  **analyze_kwargs) -> Report:
    """Trace a Layer's forward the way jit.to_static captures it —
    params and buffers threaded as labeled inputs (so PTA102 names a
    dead parameter and PTA105 does not misread weights as baked
    constants) — then run the jaxpr passes."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core import Tensor, no_grad
    from paddle_tpu.jit import _GeneratorKeyGuard
    named_params = [(n, p) for n, p in model.named_parameters()]
    named_buffers = [(n, b) for n, b in model.named_buffers()
                     if b is not None]
    n_p, n_b = len(named_params), len(named_buffers)
    # a to_static-wrapped Layer carries a StaticFunction as .forward —
    # trace its underlying function so the analysis sees flat equations
    # instead of one opaque pjit call
    forward = model.forward
    forward = getattr(forward, "_function", forward)

    def pure(key, *flat):
        params = dict((named_params[i][0], flat[i]) for i in range(n_p))
        buffers = dict((named_buffers[i][0], flat[n_p + i])
                       for i in range(n_b))
        inputs = flat[n_p + n_b:]
        with _GeneratorKeyGuard(key):
            with model._swapped_state(params, buffers):
                with no_grad():
                    out = forward(*[Tensor(a) for a in inputs])
        leaves = jax.tree_util.tree_leaves(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        return tuple(o._data if isinstance(o, Tensor) else o
                     for o in leaves)

    key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
    avals = ([_to_aval(p) for _, p in named_params] +
             [_to_aval(b) for _, b in named_buffers] +
             [_to_aval(x) for x in example_inputs])
    closed = jax.make_jaxpr(pure)(key_aval, *avals)
    labels = (["rng_key"] + [n for n, _ in named_params] +
              [n for n, _ in named_buffers] +
              [f"input[{i}]" for i in range(len(example_inputs))])
    return analyze_jaxpr(
        closed, name=name or type(model).__name__,
        invar_labels=labels, **analyze_kwargs)
