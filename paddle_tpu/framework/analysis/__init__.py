"""paddle_tpu.framework.analysis — pass-based static program analyzer.

The TPU-native analogue of the reference's inference analysis framework
(paddle/fluid/inference/analysis + framework/ir graph passes): validate
programs *before* execution so shape/dtype/donation/recompilation bugs
surface as diagnostics with stable rule IDs instead of runtime
surprises.  Two front ends share one diagnostic core:

* :mod:`.jaxpr_passes` — IR passes over ``jax.make_jaxpr`` output
  (PTA1xx): dtype upcasts, dead code, host callbacks, donation misuse,
  baked constants, FLOP/byte cost ranking.
* :mod:`.ast_passes` — jit-safety source lint (PTA2xx/PTA3xx), built on
  the dy2static analysis machinery: traced-value control flow, side
  effects under jit, tracer leaks, numpy-on-tracer, chaos fault-point
  hygiene.
* :mod:`.concurrency` — lock/thread pass family (PTA4xx): whole-repo
  lock model, acquisition-order cycles, blocking calls under locks,
  thread-shared writes, check-then-act init, finalizer-context locks,
  queue protocol, daemon writers.  Validated at runtime by the
  ``framework/locks.py`` watchdog (``FLAGS_lock_watchdog``).
* :mod:`.collectives` — distributed-semantics pass family (PTA5xx) over
  shard_map/pjit regions: unreduced mapped-axis values escaping
  replicated outputs, collective axis mismatches/double reductions,
  gather-then-slice mixing, quantized payloads summed by collectives,
  donation across collective boundaries, collectives under divergent
  conditionals.  Validated at runtime by the replica-parity probe
  (``parallel/parity.py``, ``FLAGS_replica_parity``).
* :mod:`.pallas_kernels` — Pallas kernel pass family (PTA6xx): a kernel
  model per ``pallas_call`` (grid, BlockSpec block shapes + index maps,
  kernel-body AST) checked for grid/block tail bugs, low-precision
  accumulation, output-block races, mis-anchored tail masks, analytic
  VMEM overcommit, non-static kernel control flow.  Validated at
  runtime by the interpret-vs-compiled-vs-reference differential
  oracle (``ops/pallas/verify.py``, ``FLAGS_pallas_verify``).

CLI: ``python tools/prog_lint.py <module|path> [--format=json|text]``.
Suppression: ``# pta: disable=PTA201`` inline (see diagnostics.py).
"""
from paddle_tpu.framework.analysis.ast_passes import (  # noqa: F401
    lint_file, lint_source)
from paddle_tpu.framework.analysis.collectives import (  # noqa: F401
    analyze_collectives)
from paddle_tpu.framework.analysis.concurrency import (  # noqa: F401
    analyze_files, analyze_sources, lint_threads_source)
from paddle_tpu.framework.analysis.diagnostics import (  # noqa: F401
    Diagnostic, Report, RULES, Severity)
from paddle_tpu.framework.analysis.jaxpr_passes import (  # noqa: F401
    analyze_callable, analyze_jaxpr, analyze_model)
from paddle_tpu.framework.analysis.pallas_kernels import (  # noqa: F401
    analyze_kernels, trace_kernels)

__all__ = ["Diagnostic", "Report", "RULES", "Severity", "analyze_jaxpr",
           "analyze_callable", "analyze_collectives", "analyze_kernels",
           "analyze_model", "analyze_files", "analyze_sources",
           "lint_source", "lint_file", "lint_threads_source",
           "trace_kernels"]
