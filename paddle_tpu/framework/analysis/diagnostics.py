"""Shared diagnostic/reporting core of the program analyzer.

Both analyzer front ends — the jaxpr IR passes (jaxpr_passes.py) and the
jit-safety AST linter (ast_passes.py) — emit :class:`Diagnostic` records
into one :class:`Report`, mirroring how the reference funnels every
inference analysis pass through a single Argument/AnalysisPass protocol
(paddle/fluid/inference/analysis/analysis_pass.h + framework/ir pass
registry).  One severity scale, one stable rule-ID space, one JSON/text
renderer, one suppression mechanism — so a CI gate or an editor plugin
sees a uniform stream no matter which front end found the issue.

Rule IDs are stable and namespaced by front end:

* ``PTA1xx`` — jaxpr IR passes (post-trace facts: dtypes, liveness,
  callbacks, donation, baked constants, cost model),
* ``PTA2xx`` — AST lint (pre-trace facts: control flow on traced values,
  side effects, tracer leaks, numpy-on-tracer),
* ``PTA3xx`` — cross-subsystem wiring (chaos fault-point hygiene).

Suppression: a source comment ``# pta: disable=PTA201,PTA203`` on the
offending line silences those rules there; ``# pta: disable`` silences
every rule on the line; ``# pta: disable-file=PTA105`` anywhere in the
first 10 lines silences a rule file-wide.  Jaxpr diagnostics carry no
source line, so they are filtered by rule ID via the ``disable=``
argument of the analyze entry points instead.
"""
from __future__ import annotations

import enum
import json
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = ["Severity", "Diagnostic", "Report", "RuleInfo", "RULES",
           "register_rule", "parse_suppressions", "JSON_SCHEMA_VERSION"]

JSON_SCHEMA_VERSION = 1


class Severity(enum.IntEnum):
    INFO = 10
    WARNING = 20
    ERROR = 30

    def __str__(self):  # "error", not "Severity.ERROR", in reports
        return self.name.lower()

    @classmethod
    def parse(cls, s: "str | Severity") -> "Severity":
        if isinstance(s, Severity):
            return s
        return cls[str(s).upper()]


@dataclass(frozen=True)
class RuleInfo:
    """One registered rule: the analyzer's analogue of the reference's
    REGISTER_PASS entries (framework/ir/pass.h)."""
    id: str
    title: str
    severity: Severity
    frontend: str                     # "jaxpr" | "ast" | "chaos"


RULES: Dict[str, RuleInfo] = {}


def register_rule(rule_id: str, title: str, severity: Severity,
                  frontend: str) -> RuleInfo:
    if rule_id in RULES:
        raise ValueError(f"duplicate rule id {rule_id!r}")
    info = RuleInfo(rule_id, title, severity, frontend)
    RULES[rule_id] = info
    return info


@dataclass
class Diagnostic:
    rule: str
    message: str
    severity: Severity
    file: Optional[str] = None
    line: Optional[int] = None
    col: Optional[int] = None
    hint: Optional[str] = None        # fix-hint, actionable
    frontend: str = ""                # filled from RULES when omitted

    def __post_init__(self):
        if not self.frontend and self.rule in RULES:
            self.frontend = RULES[self.rule].frontend

    @property
    def location(self) -> str:
        if self.file is None:
            return "<program>"
        loc = self.file
        if self.line is not None:
            loc += f":{self.line}"
            if self.col is not None:
                loc += f":{self.col}"
        return loc

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": str(self.severity),
                "message": self.message, "file": self.file,
                "line": self.line, "col": self.col, "hint": self.hint,
                "frontend": self.frontend}

    def render(self) -> str:
        s = f"{self.location}: {self.severity} {self.rule}: {self.message}"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s


class Report:
    """Ordered diagnostic collection with severity accounting.

    ``exit_code()`` implements the CI contract: nonzero iff any
    ERROR-severity finding survived suppression (``strict=True`` also
    promotes warnings), the role of the reference's
    paddle_build.sh stage exit codes.
    """

    def __init__(self, diagnostics: Optional[List[Diagnostic]] = None):
        self.diagnostics: List[Diagnostic] = list(diagnostics or [])
        self.files_seen: List[str] = []
        # structured PTA106 output ({total_flops, total_bytes, by_op})
        # attached by the jaxpr cost pass; None for AST-only reports
        self.cost: Optional[dict] = None

    def add(self, diag: Diagnostic):
        self.diagnostics.append(diag)

    def extend(self, other: "Report | Iterable[Diagnostic]"):
        if isinstance(other, Report):
            self.diagnostics.extend(other.diagnostics)
            self.files_seen.extend(
                f for f in other.files_seen if f not in self.files_seen)
            if self.cost is None and getattr(other, "cost", None) \
                    is not None:
                self.cost = other.cost
        else:
            self.diagnostics.extend(other)

    def filter(self, min_severity: "str | Severity" = Severity.INFO,
               disable: Sequence[str] = ()) -> "Report":
        min_severity = Severity.parse(min_severity)
        out = Report([d for d in self.diagnostics
                      if d.severity >= min_severity
                      and d.rule not in set(disable)])
        out.files_seen = list(self.files_seen)
        out.cost = self.cost
        return out

    def by_severity(self, severity: Severity) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == severity]

    @property
    def errors(self) -> List[Diagnostic]:
        return self.by_severity(Severity.ERROR)

    @property
    def warnings(self) -> List[Diagnostic]:
        return self.by_severity(Severity.WARNING)

    def counts(self) -> Dict[str, int]:
        c = {"error": 0, "warning": 0, "info": 0}
        for d in self.diagnostics:
            c[str(d.severity)] += 1
        return c

    def exit_code(self, strict: bool = False) -> int:
        if self.errors:
            return 1
        if strict and self.warnings:
            return 1
        return 0

    def to_json(self) -> str:
        return json.dumps({
            "version": JSON_SCHEMA_VERSION,
            "findings": [d.to_dict() for d in sorted(
                self.diagnostics,
                key=lambda d: (-int(d.severity), d.file or "",
                               d.line or 0, d.rule))],
            "summary": {**self.counts(),
                        "files": len(self.files_seen)},
        }, indent=1)

    def to_text(self) -> str:
        lines = [d.render() for d in sorted(
            self.diagnostics,
            key=lambda d: (d.file or "", d.line or 0, d.rule))]
        c = self.counts()
        lines.append(f"{c['error']} error(s), {c['warning']} warning(s), "
                     f"{c['info']} info over "
                     f"{len(self.files_seen)} file(s)")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# inline pragma suppression
# ---------------------------------------------------------------------------

_PRAGMA = re.compile(
    r"#\s*pta:\s*(disable-file|disable)\s*(?:=\s*([A-Z0-9, ]+))?")


@dataclass
class Suppressions:
    """Parsed ``# pta:`` pragmas of one source file."""
    by_line: Dict[int, Optional[set]] = field(default_factory=dict)
    file_wide: Optional[set] = None   # None = nothing; set() = everything
    file_wide_all: bool = False

    def allows(self, rule: str, line: Optional[int]) -> bool:
        """True when a diagnostic for ``rule`` at ``line`` survives."""
        if self.file_wide_all:
            return False
        if self.file_wide is not None and rule in self.file_wide:
            return False
        if line in self.by_line:
            rules = self.by_line[line]
            if rules is None or rule in rules:
                return False
        return True

    def allows_node(self, rule: str, node) -> bool:
        """Node-aware form of :meth:`allows`: a pragma anywhere in the
        statement's *header span* suppresses — from the first decorator
        line of a decorated ``def`` through the line before its first
        body statement, or across every line of a multi-line simple
        statement / ``with`` header.  This is what lets the pragma ride
        the line a human would naturally put it on (the decorator, the
        last line of a wrapped ``with``) instead of only the line the
        AST happens to anchor."""
        lo = getattr(node, "lineno", None)
        if node is None or lo is None:
            return self.allows(rule, None)
        for d in getattr(node, "decorator_list", None) or []:
            d_line = getattr(d, "lineno", None)
            if d_line is not None:
                lo = min(lo, d_line)
        body = getattr(node, "body", None)
        if isinstance(body, list) and body and \
                getattr(body[0], "lineno", None) is not None:
            hi = body[0].lineno - 1        # compound stmt: header only
        else:
            hi = getattr(node, "end_lineno", None) or lo
        return all(self.allows(rule, ln)
                   for ln in range(lo, max(lo, hi) + 1))


def parse_suppressions(source: str) -> Suppressions:
    sup = Suppressions()
    for i, text in enumerate(source.splitlines(), start=1):
        m = _PRAGMA.search(text)
        if not m:
            continue
        kind, ids = m.group(1), m.group(2)
        rules = ({r.strip() for r in ids.split(",") if r.strip()}
                 if ids else None)
        if kind == "disable-file":
            if i > 10:
                continue              # file pragmas live in the header
            if rules is None:
                sup.file_wide_all = True
            else:
                sup.file_wide = (sup.file_wide or set()) | rules
        else:
            sup.by_line[i] = rules
    return sup
