"""paddle.save / paddle.load parity.

Reference: python/paddle/framework/io.py:351 (save), :515 (load) — pickle of
nested state dicts with a tensor protocol.  Here tensors serialise as numpy
arrays inside a pickle; ``.pdparams``/``.pdopt`` conventions are preserved so
reference-style checkpointing code runs unchanged.  Sharded/distributed
checkpointing lives in paddle_tpu.distributed.checkpoint (per-shard files).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from paddle_tpu.core import Tensor, Parameter


_SENTINEL = b"PTPU1"


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return {"__ptpu_tensor__": True,
                "data": np.asarray(obj._data),
                "name": obj.name,
                "stop_gradient": obj.stop_gradient,
                "is_param": isinstance(obj, Parameter)}
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_serializable(v) for v in obj)
    return obj


def _from_serializable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__ptpu_tensor__"):
            if return_numpy:
                return obj["data"]
            cls = Parameter if obj.get("is_param") else Tensor
            if cls is Parameter:
                t = Parameter(obj["data"], name=obj["name"])
            else:
                t = Tensor(obj["data"], stop_gradient=obj["stop_gradient"],
                           name=obj["name"])
            return t
        return {k: _from_serializable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_serializable(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    if isinstance(path, str):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
    payload = _to_serializable(obj)
    with open(path, "wb") as f:
        f.write(_SENTINEL)
        pickle.dump(payload, f, protocol=protocol)


def dumps(obj: Any, protocol: int = 4) -> bytes:
    """save() to an in-memory payload (the encrypted-model path —
    plaintext weights never touch disk)."""
    return _SENTINEL + pickle.dumps(_to_serializable(obj),
                                    protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs):
    # streamed, not slurped: multi-GB checkpoints must not hold an
    # extra whole-file copy in RAM
    with open(path, "rb") as f:
        head = f.read(len(_SENTINEL))
        if head != _SENTINEL:
            f.seek(0)
        payload = pickle.load(f)
    return _from_serializable(payload, return_numpy=return_numpy)


def loads(data: bytes, return_numpy: bool = False):
    """load() from an in-memory payload (the decrypted-model path)."""
    import io as _io
    buf = _io.BytesIO(data)
    if buf.read(len(_SENTINEL)) != _SENTINEL:
        buf.seek(0)
    payload = pickle.load(buf)
    return _from_serializable(payload, return_numpy=return_numpy)
