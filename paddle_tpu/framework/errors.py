"""Typed error taxonomy + enforce helpers.

Reference: paddle/fluid/platform/errors.h (the 12 REGISTER_ERROR types at
:71-82) and enforce.h's PADDLE_ENFORCE_* macro family — every kernel and
framework check raises a *typed* error with an actionable message, and
the python layer re-exports the types (fluid/core EnforceNotMet
subclasses).

The enforce helpers mirror the macros' spirit: one-line checks that
produce the reference's "Expected X, but received Y" message shape, so
error text stays greppable across the two codebases.
"""
from __future__ import annotations

__all__ = [
    "EnforceNotMet", "InvalidArgumentError", "NotFoundError",
    "OutOfRangeError", "AlreadyExistsError", "ResourceExhaustedError",
    "PreconditionNotMetError", "PermissionDeniedError",
    "ExecutionTimeoutError", "UnimplementedError", "UnavailableError",
    "FatalError", "ExternalError",
    "enforce", "enforce_eq", "enforce_gt", "enforce_ge", "enforce_lt",
    "enforce_le", "enforce_not_none", "enforce_shape",
]


class EnforceNotMet(RuntimeError):
    """Base of all typed framework errors (enforce.h EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, LookupError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet, PermissionError):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class FatalError(EnforceNotMet):
    pass


class ExternalError(EnforceNotMet):
    pass


def enforce(cond, msg: str, error=InvalidArgumentError):
    """PADDLE_ENFORCE(cond, ...): raise typed error when cond is false."""
    if not cond:
        raise error(msg)


def _cmp(a, b, op, sym, msg, error):
    if not op(a, b):
        raise error(f"Expected {a!r} {sym} {b!r}."
                    + (f" {msg}" if msg else ""))


def enforce_eq(a, b, msg: str = "", error=InvalidArgumentError):
    _cmp(a, b, lambda x, y: x == y, "==", msg, error)


def enforce_gt(a, b, msg: str = "", error=InvalidArgumentError):
    _cmp(a, b, lambda x, y: x > y, ">", msg, error)


def enforce_ge(a, b, msg: str = "", error=InvalidArgumentError):
    _cmp(a, b, lambda x, y: x >= y, ">=", msg, error)


def enforce_lt(a, b, msg: str = "", error=InvalidArgumentError):
    _cmp(a, b, lambda x, y: x < y, "<", msg, error)


def enforce_le(a, b, msg: str = "", error=InvalidArgumentError):
    _cmp(a, b, lambda x, y: x <= y, "<=", msg, error)


def enforce_not_none(v, name: str = "value", error=NotFoundError):
    if v is None:
        raise error(f"Expected {name} to be set, but received None.")
    return v


def enforce_shape(tensor, expected, name: str = "tensor",
                  error=InvalidArgumentError):
    """Shape check with -1 wildcards (the ENFORCE pattern of every
    InferShape): enforce_shape(x, [None, 3], "x")."""
    shape = list(getattr(tensor, "shape", tensor))
    if len(shape) != len(expected) or any(
            e not in (None, -1) and int(e) != int(s)
            for s, e in zip(shape, expected)):
        raise error(f"Expected {name}.shape compatible with "
                    f"{list(expected)}, but received {shape}.")
