"""Framework-level utilities: flags, io, RNG re-exports.

The reference's L3 core (ProgramDesc/Executor/Scope) has no equivalent here —
XLA is that machinery.  What remains framework-level is the typed flag/config
system (replacing gflags + env bootstrap, reference: platform/flags.cc,
pybind/global_value_getter_setter.cc:330) and serialization.
"""
from paddle_tpu.framework import flags  # noqa: F401
from paddle_tpu.framework import monitor  # noqa: F401
from paddle_tpu.framework import auto_checkpoint  # noqa: F401
from paddle_tpu.framework import analysis  # noqa: F401
from paddle_tpu.framework import chaos  # noqa: F401
from paddle_tpu.framework import errors  # noqa: F401
from paddle_tpu.framework import observability  # noqa: F401
from paddle_tpu.framework.resilient import ResilientTrainStep  # noqa: F401
from paddle_tpu.framework.io import save, load  # noqa: F401
from paddle_tpu.tensor.random import (  # noqa: F401
    seed, get_rng_state, set_rng_state, default_generator, Generator)
from paddle_tpu.core import (  # noqa: F401
    Tensor, Parameter, CPUPlace, TPUPlace, CUDAPlace, get_default_dtype,
    set_default_dtype, no_grad)


def _current_expected_place():
    from paddle_tpu.core import get_device, _place_of
    return _place_of(get_device())


def in_dygraph_mode():
    from paddle_tpu import static
    return not static._in_static_mode()
