"""Model-numerics observability plane: in-jit tensor stats, NaN
provenance, and gradient-drift signals.

The reference ships a per-op NaN/Inf watcher (``FLAGS_check_nan_inf``,
framework/details/nan_inf_utils.h) that names *which op* blew up; this
repo's rollback tier (framework/resilient.py) only knew "the loss went
non-finite" after a host sync, and nothing watched gradient magnitudes
at all.  This module is the model-signal twin of the PR-7 system-health
plane (framework/health.py): cheap reductions computed **inside the
jitted step** — per-leaf and global grad norms, param norms,
update/param ratios, max-abs, and non-finite counts — returned as
auxiliary outputs of ``TrainStep`` / ``PSTrainStep`` /
``ShardedUpdateTrainStep`` and published into the existing planes
(monitor gauges + histograms, health detectors, flight recorder).

Design center, same as the health plane:

* **cheap when off** — arming is ``FLAGS_numerics``; disarmed, the step
  classes build exactly the seed computation (no extra outputs, no
  recompile: the signature-cache key only grows a marker when armed),
  and the per-step cost is one flag read;
* **no host callbacks, no extra device syncs** — the stats are O(#leaf)
  scalar reductions fused into the step's own XLA computation and ride
  back with its outputs; the host reads them where it already
  synchronizes (the loss / finite check);
* **shard-map aware** — under ``ShardedUpdateTrainStep`` each leaf is a
  1/dp chunk: sum-of-squares and non-finite counts are computed
  shard-locally and ``psum``-ed, max-abs ``pmax``-ed (the global-norm
  clip idiom in parallel/zero.py), so the exported global grad norm is
  the replicated step's norm bit-for-bit-comparable;
* **NaN provenance** — the per-leaf non-finite counts name the first
  offending parameter leaf (sorted leaf-name order);
  ``ResilientTrainStep`` stamps it into the ``train.nan_skip`` flight
  event as ``first_bad_leaf`` and uses the same aux as its in-jit
  finite check (the previous per-step host ``np.isfinite`` param sweep
  disappears);
* **the watcher never crashes the watched** — host-side publishing runs
  behind the ``numerics.observe`` chaos fault point: an injected error
  is swallowed and counted (``numerics_observe_errors_total``).

Exported metrics (monitor):

==============================  ============================================
``grad_norm`` (histogram)        global L2 grad norm per step
``param_norm`` (histogram)       global L2 param norm per step
``update_ratio`` (histogram)     global update-norm / param-norm per step
``numerics_grad_norm`` …         gauges: the latest global values
``numerics_grad_norm[<leaf>]``…  per-leaf gauges at the sampled cadence
                                 (``FLAGS_numerics_sample_every``); the
                                 bracketed suffix exports as a
                                 Prometheus ``leaf`` label
``numerics_nonfinite_steps_total``  steps with any non-finite stat
``numerics_observe_errors_total``   swallowed publish faults
==============================  ============================================

Detector feed: every global value is offered to ``health.observe``
under the signals ``grad_norm`` / ``update_ratio`` (both in
``health.DEFAULT_SIGNALS``) — a 10× grad spike trips the default
detector the step it lands, and a NON-finite value is an anomaly by
definition (``Detector``'s z=inf rule: flagged immediately, never
folded into the EWMA or baseline window), so the detector fires AT
the blown-up step and the provenance record names the leaf.
Histograms only ever record finite values.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.framework import chaos, monitor
from paddle_tpu.framework.flags import flag

__all__ = ["AUX_KEYS", "DRIFT_SIGNALS", "enabled", "sample_every",
           "compute_aux", "NumericsRecord", "publish", "watch_defaults",
           "reset"]

#: the aux pytree every armed step returns — per-leaf f32/int32 vectors
#: of length L (#parameter leaves) plus one scalar for the loss
AUX_KEYS = ("grad_sq", "param_sq", "update_sq", "grad_maxabs",
            "grad_nonfinite", "param_nonfinite", "loss_nonfinite")

#: the drift signals this plane feeds — the per-signal detector kwargs
#: live in ``health.DEFAULT_SIGNALS`` (one source of truth; the grad
#: norm entries there document the floor rationale), so
#: ``FLAGS_health_detectors=default`` arms them too
DRIFT_SIGNALS = ("grad_norm", "update_ratio")


def enabled() -> bool:
    """True when the in-jit stats are armed (``FLAGS_numerics``)."""
    return bool(flag("numerics"))


def sample_every() -> int:
    """Per-leaf export cadence (``FLAGS_numerics_sample_every``): the
    per-leaf gauges refresh every Nth published step; 0 disables the
    per-leaf export (global gauges/histograms still publish every
    step)."""
    return int(flag("numerics_sample_every"))


# ---------------------------------------------------------------------------
# in-jit computation (traced inside the step)
# ---------------------------------------------------------------------------

def compute_aux(grads: dict, params: dict, new_params: dict, loss,
                axis_name: Optional[str] = None) -> dict:
    """Build the numerics aux pytree INSIDE a traced step.

    ``grads`` / ``params`` / ``new_params`` are same-keyed dicts of
    (possibly shard-local) arrays; ``loss`` the step's scalar loss.
    Leaf order is SORTED key order — jax's pytree flattening sorts
    dict keys, so a dict that crossed a jit boundary iterates sorted
    while one built inside the trace iterates in insertion order;
    sorting here pins one canonical order for both, and the step
    classes build their :class:`NumericsRecord` with
    ``sorted(names)`` to match.

    Under ``shard_map`` pass ``axis_name``: sum-of-squares and
    non-finite counts reduce shard-locally then ``psum`` (padding
    chunks contribute exact zeros), max-abs ``pmax``-es — every replica
    leaves with the identical global vectors, so the aux satisfies a
    replicated out_spec.  The loss must already be replicated (the
    steps ``pmean`` it first).
    """
    import jax
    import jax.numpy as jnp

    names = sorted(grads)
    f32 = jnp.float32

    def _stack(vals, dtype):
        if not names:
            return jnp.zeros((0,), dtype)
        return jnp.stack(vals).astype(dtype)

    def _nonfinite(a):
        if not jnp.issubdtype(a.dtype, jnp.inexact):
            return jnp.zeros((), jnp.int32)
        return jnp.sum(~jnp.isfinite(a)).astype(jnp.int32)

    gsq = _stack([jnp.sum(grads[n].astype(f32) ** 2) for n in names], f32)
    psq = _stack([jnp.sum(params[n].astype(f32) ** 2) for n in names], f32)
    usq = _stack([jnp.sum((new_params[n].astype(f32)
                           - params[n].astype(f32)) ** 2)
                  for n in names], f32)
    gmax = _stack([(jnp.max(jnp.abs(grads[n].astype(f32)))
                    if grads[n].size else jnp.zeros((), f32))
                   for n in names], f32)
    gnf = _stack([_nonfinite(grads[n]) for n in names], jnp.int32)
    pnf = _stack([_nonfinite(new_params[n]) for n in names], jnp.int32)
    loss_arr = jnp.asarray(loss)
    lnf = jnp.sum(~jnp.isfinite(loss_arr.astype(f32))).astype(jnp.int32)
    if axis_name is not None:
        gsq = jax.lax.psum(gsq, axis_name)
        psq = jax.lax.psum(psq, axis_name)
        usq = jax.lax.psum(usq, axis_name)
        gmax = jax.lax.pmax(gmax, axis_name)
        gnf = jax.lax.psum(gnf, axis_name)
        pnf = jax.lax.psum(pnf, axis_name)
        # loss is pmean-ed by the sharded steps before it gets here, so
        # lnf is already identical on every replica — no reduce needed
    return {"grad_sq": gsq, "param_sq": psq, "update_sq": usq,
            "grad_maxabs": gmax, "grad_nonfinite": gnf,
            "param_nonfinite": pnf, "loss_nonfinite": lnf}


# ---------------------------------------------------------------------------
# host-side record
# ---------------------------------------------------------------------------

class NumericsRecord:
    """One step's numerics aux, host side.

    Holds the device arrays and converts them to numpy LAZILY on first
    read (one fetch for all keys — by then the step's computation has
    completed anyway, so this is the same sync reading the loss pays).
    Global norms derive from the per-leaf sum-of-squares; update_ratio
    is update-norm / param-norm (0 when the param norm is 0).

    ``names`` is canonicalized to sorted order — the order
    :func:`compute_aux` stacked the per-leaf vectors in.
    """

    __slots__ = ("names", "step", "_aux", "_np")

    def __init__(self, names: List[str], aux: dict,
                 step: Optional[int] = None):
        self.names = sorted(names)
        self.step = step
        self._aux = aux
        self._np: Optional[Dict[str, np.ndarray]] = None

    def _fetch(self) -> Dict[str, np.ndarray]:
        if self._np is None:
            self._np = {k: np.asarray(v) for k, v in self._aux.items()}
            self._aux = None          # drop the device refs once read
        return self._np

    # -- global scalars ------------------------------------------------------
    @staticmethod
    def _norm(sq) -> float:
        """sqrt of a sum-of-squares, NaN/Inf-PROPAGATING: ``max(0.0,
        nan)`` is 0.0 in Python, so a naive clamp would silently report
        a blown-up step as a zero norm — exactly the value that would
        poison a drift detector's baseline while hiding the blow-up."""
        s = float(sq)
        if math.isnan(s):
            return s
        return math.sqrt(max(0.0, s))

    @property
    def grad_norm(self) -> float:
        return self._norm(self._fetch()["grad_sq"].sum())

    @property
    def param_norm(self) -> float:
        return self._norm(self._fetch()["param_sq"].sum())

    @property
    def update_norm(self) -> float:
        return self._norm(self._fetch()["update_sq"].sum())

    @property
    def update_ratio(self) -> float:
        p = self.param_norm
        if math.isnan(p):
            return p
        return self.update_norm / p if p > 0.0 else 0.0

    @property
    def max_abs_grad(self) -> float:
        a = self._fetch()["grad_maxabs"]
        return float(a.max()) if a.size else 0.0

    @property
    def nonfinite_grads(self) -> int:
        return int(self._fetch()["grad_nonfinite"].sum())

    @property
    def nonfinite_params(self) -> int:
        return int(self._fetch()["param_nonfinite"].sum())

    @property
    def nonfinite_loss(self) -> int:
        return int(self._fetch()["loss_nonfinite"])

    # -- provenance ----------------------------------------------------------
    def finite(self, check_params: bool = True) -> bool:
        """The in-jit finite verdict: loss and every grad leaf finite
        (and every post-update param leaf when ``check_params`` — the
        ``check_state=True`` sweep of ResilientTrainStep, now free)."""
        if self.nonfinite_loss or self.nonfinite_grads:
            return False
        if check_params and self.nonfinite_params:
            return False
        return True

    def first_bad_leaf(self) -> Optional[str]:
        """The first parameter leaf (sorted leaf-name order) with a
        non-finite gradient — falling back to the first leaf with a
        non-finite post-update param, then None (loss-only blow-up)."""
        a = self._fetch()
        for key in ("grad_nonfinite", "param_nonfinite"):
            bad = np.nonzero(a[key])[0]
            if bad.size:
                return self.names[int(bad[0])]
        return None

    def bad_leaves(self) -> List[str]:
        """Every leaf with a non-finite grad or post-update param."""
        a = self._fetch()
        mask = (a["grad_nonfinite"] > 0) | (a["param_nonfinite"] > 0)
        return [n for n, m in zip(self.names, mask) if m]

    # -- per-leaf view -------------------------------------------------------
    def per_leaf(self) -> Dict[str, dict]:
        a = self._fetch()
        out = {}
        for i, n in enumerate(self.names):
            pn = self._norm(a["param_sq"][i])
            un = self._norm(a["update_sq"][i])
            # NaN-propagating like the global property: `nan > 0.0` is
            # False, and 0.0 would read as a healthy leaf
            ratio = pn if math.isnan(pn) else (
                un / pn if pn > 0.0 else 0.0)
            out[n] = {
                "grad_norm": self._norm(a["grad_sq"][i]),
                "param_norm": pn,
                "update_ratio": ratio,
                "max_abs_grad": float(a["grad_maxabs"][i]),
                "nonfinite": int(a["grad_nonfinite"][i]
                                 + a["param_nonfinite"][i]),
            }
        return out

    def to_dict(self) -> dict:
        return {"step": self.step, "grad_norm": self.grad_norm,
                "param_norm": self.param_norm,
                "update_ratio": self.update_ratio,
                "max_abs_grad": self.max_abs_grad,
                "nonfinite": {"loss": self.nonfinite_loss,
                              "grads": self.nonfinite_grads,
                              "params": self.nonfinite_params},
                "first_bad_leaf": self.first_bad_leaf()}

    def __repr__(self):
        return (f"NumericsRecord(step={self.step} "
                f"grad_norm={self.grad_norm:.4g} "
                f"update_ratio={self.update_ratio:.4g} "
                f"nonfinite={self.nonfinite_grads + self.nonfinite_params + self.nonfinite_loss})")


# ---------------------------------------------------------------------------
# publishing (gauges, histograms, detectors, per-leaf sampling)
# ---------------------------------------------------------------------------

_publish_calls = 0
_publish_lock = threading.Lock()


def publish(record: NumericsRecord) -> Optional[NumericsRecord]:
    """Fold one step's record into the monitor/health planes.

    Global gauges + histograms every call; per-leaf gauges at the
    ``FLAGS_numerics_sample_every`` cadence.  Global values feed the
    ``grad_norm`` / ``update_ratio`` health detectors — a non-finite
    value flags immediately (Detector z=inf rule) while staying out of
    the baselines and histograms, and is counted
    (``numerics_nonfinite_steps_total``).  The ``numerics.observe``
    chaos fault point fires at the
    head: an injected error is swallowed and counted — the watcher must
    never crash the watched train step.  Returns the record (None when
    a fault swallowed the publish).
    """
    from paddle_tpu.framework import health
    try:
        chaos.fault_point("numerics.observe",
                          meta={"step": record.step})
    except chaos.InjectedFault:
        # the watcher must never crash the watched: swallow, count
        monitor.stat_add("numerics_observe_errors_total")
        return None
    g, p, r, mx = (record.grad_norm, record.param_norm,
                   record.update_ratio, record.max_abs_grad)
    monitor.stat_set("numerics_grad_norm", g)
    monitor.stat_set("numerics_param_norm", p)
    monitor.stat_set("numerics_update_ratio", r)
    monitor.stat_set("numerics_max_abs_grad", mx)
    nonfinite = (record.nonfinite_loss or record.nonfinite_grads
                 or record.nonfinite_params)
    if nonfinite:
        monitor.stat_add("numerics_nonfinite_steps_total")
    for name, v in (("grad_norm", g), ("param_norm", p),
                    ("update_ratio", r)):
        if np.isfinite(v):
            monitor.observe(name, v)
        if name != "param_norm":
            # drift detectors see every value: a non-finite one flags
            # immediately (Detector's z=inf rule) without ever entering
            # the baseline — the detector fires AT the blown-up step,
            # provenance then names the leaf
            health.observe(name, v)
    global _publish_calls
    every = sample_every()
    due = False
    if every > 0:
        with _publish_lock:
            _publish_calls += 1
            due = _publish_calls % every == 0
        due = due or bool(nonfinite)
    # per-leaf attribution: sampled on the healthy path (L gauges per
    # refresh is the whole cost), always on a non-finite step — the
    # post-mortem wants the leaf split exactly then.  every=0 is a HARD
    # off (the operator's metric-cardinality cap; NaN provenance still
    # reaches the flight event via first_bad_leaf, not these gauges)
    if due:
        for leaf, d in record.per_leaf().items():
            monitor.stat_set(f"numerics_grad_norm[{leaf}]",
                             d["grad_norm"])
            monitor.stat_set(f"numerics_update_ratio[{leaf}]",
                             d["update_ratio"])
            monitor.stat_set(f"numerics_max_abs_grad[{leaf}]",
                             d["max_abs_grad"])
            if d["nonfinite"]:
                monitor.stat_add(f"numerics_nonfinite[{leaf}]",
                                 d["nonfinite"])
    return record


def watch_defaults(**overrides):
    """Arm the plane's default drift detectors (:data:`DRIFT_SIGNALS`)
    on the process health monitor — idempotent, like every
    ``health.watch``.  ``overrides`` update the per-signal kwargs
    (e.g. ``warmup=8`` for short test runs)."""
    from paddle_tpu.framework import health
    dets = {}
    for signal in DRIFT_SIGNALS:
        kw = dict(health.DEFAULT_SIGNALS.get(signal, {}))
        kw.update(overrides)
        dets[signal] = health.watch(signal, **kw)
    return dets


def reset():
    """Per-test clean slate for the publish cadence counter (gauges and
    detectors are owned by monitor/health reset as usual)."""
    global _publish_calls
    with _publish_lock:
        _publish_calls = 0
