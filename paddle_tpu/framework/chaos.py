"""Deterministic fault-injection ("chaos") registry.

The reference treats failure handling as a first-class subsystem —
heartbeat lost-worker monitoring (operators/distributed/
heart_beat_monitor.cc), auto-checkpoint crash recovery
(fluid/incubate/checkpoint/auto_checkpoint.py TrainEpochRange), per-op
NaN/Inf watching (FLAGS_check_nan_inf) — but none of it is provable
without a way to *cause* the faults on demand.  This module is that
way: a seedable registry of named fault points threaded through the
layers that must survive them.

Fault points shipped in-tree (grep for ``fault_point(`` to audit):

=====================  ====================================================
``ps.rpc``              client side of every PS RPC (ps/service.py
                        _Conn.rpc)
``ps.pipeline``         each background prefetch task of PSTrainStep's
                        pull/compute overlap pipeline (ps/__init__.py
                        _issue_prefetch) — ``mode="error"`` is a failed
                        prefetch (the step must fall back to a
                        synchronous pull and replay the coalesced
                        push), ``mode="latency"`` a slow one the
                        consume path must simply wait out
``data.pipeline``       each background fetch+transfer task of the
                        streaming ingest plane (io/pipeline.py
                        IngestPipeline) — ``mode="error"`` is a failed
                        prefetch (the consumer must fall back to a
                        synchronous fetch+transfer of the same batch:
                        no sample lost, no duplicate), ``mode="latency"``
                        a slow decode the wait stage simply absorbs
``fs.write``            crash-safe file writes (fleet/utils/fs.py
                        atomic_write)
``ckpt.save``           per-file checkpoint writes (distributed/
                        checkpoint.py)
``ckpt.async``          async-save dispatch (distributed/checkpoint.py
                        ``save_train_state(mode="async")``) — an
                        injected fault means the background tier is
                        broken; the save degrades to a counted
                        synchronous save, never to no save
``ckpt.verify``         checkpoint integrity verification
                        (distributed/checkpoint.py verify_checkpoint)
                        — an injected fault makes the verifier itself
                        fail closed: the checkpoint is reported
                        unverifiable, save-side commit refuses, and
                        load walks back a generation
``download.fetch``      each fetch attempt (utils/download.py)
``train.step_grads``    per-step input poisoning (framework/resilient.py)
                        — ``mode="nan"`` with ``payload_index=i``
                        poisons only the i-th step input, so the NaN
                        reaches exactly the parameter leaves that input
                        feeds (the numerics plane's per-leaf provenance
                        fault)
``elastic.lease``       every lease renewal (distributed/elastic.py
                        RendezvousStore.renew) — ``mode="error"`` is a
                        lost renewal: the lease runs out, a peer's sweep
                        expires it, the membership epoch bumps
``elastic.worker_hang`` per-step worker liveness beat (elastic.py
                        ElasticWorkerContext.step_done) —
                        ``mode="latency"`` is a straggler/hung worker the
                        agent's hang deadline must catch
``health.detector``     head of every health-plane observation
                        (framework/health.py HealthMonitor.observe) —
                        ``mode="error"`` is a broken detector the
                        observe path must swallow and count (the
                        watcher must never crash the watched train
                        loop), ``mode="latency"`` a slow one the loop
                        simply absorbs
``zero.collective``     once per collective leg (reduce_scatter /
                        all_gather) at the dispatch head of the ZeRO
                        sharded update (parallel/zero.py
                        ShardedUpdateTrainStep) — ``mode="error"`` is a
                        dropped collective the step re-issues (bounded
                        pre-dispatch retry; no state was consumed, so
                        the retried trajectory is bit-identical),
                        ``mode="latency"`` a slow interconnect the
                        dispatch simply absorbs
``numerics.observe``    head of every model-numerics publish
                        (framework/numerics.py publish) —
                        ``mode="error"`` is a broken stats exporter the
                        publish path must swallow and count
                        (``numerics_observe_errors_total``): the
                        watcher must never crash the watched train
                        step; ``mode="latency"`` a slow one the step
                        simply absorbs
``runlog.observe``      head of every run-ledger append
                        (framework/runlog.py RunLedger.append) —
                        ``mode="error"`` is a broken/full ledger disk
                        the append must swallow and count
                        (``runlog_write_errors_total`` + a
                        ``runlog.write_error`` flight event): the run
                        being recorded must never crash on its
                        recorder; ``mode="latency"`` a slow disk the
                        append simply absorbs
``locks.observe``       head of every lock-watchdog observation
                        (framework/locks.py LockWatchdog.note_acquire,
                        armed via FLAGS_lock_watchdog) —
                        ``mode="error"`` is broken watchdog bookkeeping
                        the observation path must swallow and count
                        (``lock_watchdog_errors_total``): the watcher
                        must never deadlock or crash the watched lock;
                        ``mode="latency"`` a slow observation the
                        acquire simply absorbs
``collector.rpc``       head of every telemetry push the
                        fire-and-forget sender thread attempts
                        (framework/collector.py CollectorClient) —
                        ``mode="error"`` is a dead/refusing collector:
                        the payload is DROPPED and counted
                        (``collector_dropped_total``), the pushing
                        train loop is bit-identical to a collector-less
                        run; ``mode="latency"`` a slow collector the
                        sender thread absorbs off the training path
``autopilot.act``       head of every autopilot actuator application
                        (framework/autopilot.py Controller._apply,
                        armed via FLAGS_autopilot) — ``mode="error"``
                        is a faulting actuator the controller must
                        swallow and count
                        (``autopilot_act_errors_total`` + an
                        ``autopilot.act_error`` flight event): the
                        controller must never crash the run it
                        steers; ``mode="latency"`` a slow actuator
                        the evaluation interval simply absorbs
``parity.observe``      head of every replica-parity probe observation
                        (parallel/parity.py ParityProbe.observe, armed
                        via FLAGS_replica_parity) — ``mode="error"`` is
                        a broken probe the observation path must
                        swallow and count
                        (``parity_observe_errors_total``): the watcher
                        must never perturb or crash the watched train
                        step (the trajectory stays bit-identical);
                        ``mode="latency"`` a slow probe the step simply
                        absorbs
``pallas.verify``       head of every Pallas differential-oracle check
                        (ops/pallas/verify.py verify_call, armed via
                        FLAGS_pallas_verify) — ``mode="error"`` is a
                        broken oracle the verification path must
                        swallow and count
                        (``pallas_verify_errors_total``): the watcher
                        must never perturb or crash the watched kernel
                        call (its output stays bit-identical);
                        ``mode="latency"`` a slow oracle the call
                        simply absorbs
``incident.capture``    head of every incident-bundle capture
                        (framework/incident.py IncidentRecorder, armed
                        via FLAGS_incident) — ``mode="error"`` is a
                        broken/full bundle disk the capture must
                        swallow and count
                        (``incident_capture_errors_total``): the
                        postmortem recorder must never crash the run
                        it records; ``mode="latency"`` a slow disk the
                        (already off-hot-path) capture simply absorbs
=====================  ====================================================

Injection is schedule-driven and deterministic: ``nth`` (trip exactly on
the Nth call), ``every`` (trip every Nth call), ``p`` (seeded
probability), bounded by ``n_times``.  A trip applies the point's
``mode``: ``"error"`` raises :class:`InjectedFault`, ``"latency"``
sleeps ``latency`` seconds then proceeds, ``"nan"`` NaN-poisons float
arrays in the payload and returns them.

Arming paths, in precedence order:

* the :func:`inject` context manager (tests):
    ``with chaos.inject("ps.rpc", mode="error", nth=3): ...``
* env flags read once at first use (so a launcher can arm a whole
  child-process tree): ``FLAGS_chaos_spec`` is a JSON object
  ``{"<point>": {"mode": ..., "nth": ..., ...}}``, ``FLAGS_chaos_seed``
  seeds the probability stream.

When nothing is armed a fault point is one dict lookup — cheap enough
to leave in production paths.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["InjectedFault", "FaultSpec", "fault_point", "inject", "arm",
           "disarm", "stats", "reset", "arm_from_flags", "FAULT_POINTS",
           "register_fault_point", "known_fault_points",
           "payload_fault_points", "arm_state", "restore_state"]

FAULT_POINTS = ("ps.rpc", "ps.pipeline", "data.pipeline", "fs.write",
                "ckpt.save", "ckpt.async", "ckpt.verify",
                "download.fetch", "train.step_grads",
                "elastic.lease", "elastic.worker_hang",
                "health.detector", "zero.collective",
                "numerics.observe", "runlog.observe", "collector.rpc",
                "locks.observe", "parity.observe", "autopilot.act",
                "pallas.verify", "incident.capture")
_known_points = set(FAULT_POINTS)
# points whose fault_point() call carries a payload (the only ones where
# mode="nan" can transform anything)
_payload_points = {"train.step_grads"}


def register_fault_point(name: str, carries_payload: bool = False):
    """Declare a custom fault point so arm()/FLAGS_chaos_spec accept it.
    In-tree points are pre-registered; arming an UNDECLARED name raises —
    a typo'd spec silently injecting nothing is exactly the
    false-green-chaos-run this registry exists to prevent.  Pass
    ``carries_payload=True`` when your fault_point() call site hands in
    arrays, to unlock ``mode="nan"`` for it."""
    _known_points.add(name)
    if carries_payload:
        _payload_points.add(name)
    return name


def known_fault_points() -> frozenset:
    """Every declared fault point name — in-tree plus anything added via
    :func:`register_fault_point`.  Consumer API for the static analyzer
    (framework.analysis rules PTA301/PTA302): the linter validates
    ``fault_point("...")`` call sites against this registry and flags
    sites with no retry/backoff guard, so a chaos-armed point can never
    be a name the registry would reject nor a call path that escalates
    an injected fault straight into a crash."""
    return frozenset(_known_points)


def payload_fault_points() -> frozenset:
    """Declared points whose call sites carry a payload (the only ones
    where ``mode="nan"`` transforms anything) — see known_fault_points."""
    return frozenset(_payload_points)


class InjectedFault(ConnectionError):
    """Raised by an armed ``mode="error"`` fault point.

    Subclasses ConnectionError so transport-layer retry paths (PS RPC)
    treat an injected drop exactly like a real one; elsewhere it
    propagates like the crash it simulates."""


class FaultSpec:
    """One armed fault point's schedule + mode."""

    def __init__(self, mode: str = "error", nth: Optional[int] = None,
                 every: Optional[int] = None, p: float = 0.0,
                 latency: float = 0.0, n_times: Optional[int] = None,
                 message: str = "", payload_index: Optional[int] = None):
        if mode not in ("error", "latency", "nan"):
            raise ValueError(f"unknown chaos mode {mode!r}")
        self.mode = mode
        self.nth = nth
        self.every = every
        self.p = float(p)
        self.latency = float(latency)
        self.n_times = n_times
        self.message = message
        # mode="nan" targeting: poison only the payload_index-th element
        # of a tuple/list payload (e.g. ONE input of a train step, so a
        # NaN reaches exactly the parameter leaves that input feeds —
        # the numerics plane's per-leaf provenance is provable only
        # with a fault this surgical); None poisons every float array
        self.payload_index = payload_index
        self.calls = 0
        self.trips = 0

    def should_trip(self, rng: np.random.Generator) -> bool:
        self.calls += 1
        if self.n_times is not None and self.trips >= self.n_times:
            return False
        hit = False
        if self.nth is not None and self.calls == self.nth:
            hit = True
        if self.every is not None and self.calls % self.every == 0:
            hit = True
        if self.p > 0.0 and rng.random() < self.p:
            hit = True
        if hit:
            self.trips += 1
        return hit


class ChaosRegistry:
    def __init__(self, seed: int = 0):
        self._specs: Dict[str, FaultSpec] = {}
        self._seed = int(seed)
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self.armed = False               # fast-path gate for fault_point

    def arm(self, name: str, **spec) -> FaultSpec:
        if name not in _known_points:
            raise ValueError(
                f"unknown fault point {name!r} — in-tree points: "
                f"{sorted(_known_points)}; declare custom sites with "
                "register_fault_point() first")
        if spec.get("mode") == "nan" and name not in _payload_points:
            raise ValueError(
                f"fault point {name!r} carries no payload — mode='nan' "
                "would inject nothing (false-green chaos); payload "
                f"points: {sorted(_payload_points)}")
        fs = FaultSpec(**spec)
        with self._lock:
            self._specs[name] = fs
            self.armed = True
        return fs

    def disarm(self, name: Optional[str] = None):
        with self._lock:
            if name is None:
                self._specs.clear()
            else:
                self._specs.pop(name, None)
            self.armed = bool(self._specs)

    def reseed(self, seed: int):
        # under the registry lock: fire() reads the generator under it,
        # and a reseed racing a fire must swap the reference atomically
        # with the schedule state (PTA403)
        with self._lock:
            self._seed = int(seed)
            self._rng = np.random.default_rng(seed)

    def export_state(self) -> Dict[str, Any]:
        """JSON-able snapshot of the whole injection state: seed, the
        probability stream's mid-sequence generator state, and every
        armed spec WITH its call/trip counters — what an incident
        bundle records so a replay resumes the exact fault schedule a
        mid-run incident saw, not the schedule from call zero."""
        with self._lock:
            specs = {}
            for name, s in self._specs.items():
                specs[name] = {
                    "mode": s.mode, "nth": s.nth, "every": s.every,
                    "p": s.p, "latency": s.latency, "n_times": s.n_times,
                    "message": s.message,
                    "payload_index": s.payload_index,
                    "calls": s.calls, "trips": s.trips}
            return {"seed": self._seed, "armed": self.armed,
                    "rng_state": self._rng.bit_generator.state,
                    "specs": specs}

    def import_state(self, state: Dict[str, Any]):
        """Reinstall an :meth:`export_state` snapshot: specs are rebuilt
        with their call/trip counters reinstated, and the probability
        stream resumes from the recorded generator state (falling back
        to a fresh seed when the snapshot predates ``rng_state``)."""
        specs = {}
        for name, kw in dict(state.get("specs") or {}).items():
            kw = dict(kw)
            calls = int(kw.pop("calls", 0))
            trips = int(kw.pop("trips", 0))
            fs = FaultSpec(**kw)
            fs.calls, fs.trips = calls, trips
            specs[name] = fs
        with self._lock:
            self._seed = int(state.get("seed", 0))
            self._rng = np.random.default_rng(self._seed)
            rng_state = state.get("rng_state")
            if rng_state is not None:
                self._rng.bit_generator.state = rng_state
            self._specs = specs
            self.armed = bool(specs)

    def fire(self, name: str, payload: Any = None, meta: dict = None):
        spec = self._specs.get(name)
        if spec is None:
            return payload
        with self._lock:
            trip = spec.should_trip(self._rng)
        if not trip:
            return payload
        # every trip lands in the flight recorder: a post-mortem dump
        # shows the injected fault right before the recovery machinery's
        # own events (retry, mark_dead, rollback, re-form)
        from paddle_tpu.framework.observability import flight
        flight.record("chaos.trip", severity="warn", point=name,
                      mode=spec.mode, call=spec.calls,
                      **({"meta": meta} if meta else {}))
        if spec.mode == "latency":
            time.sleep(spec.latency)
            return payload
        if spec.mode == "nan":
            return _poison(payload, index=spec.payload_index)
        raise InjectedFault(
            f"chaos[{name}] injected fault (call {spec.calls}"
            + (f", {meta}" if meta else "") + ")"
            + (f": {spec.message}" if spec.message else ""))

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {n: {"calls": s.calls, "trips": s.trips}
                    for n, s in self._specs.items()}


def _poison(payload, index=None):
    """NaN-poison every float array in ``payload`` (first element of each
    array, enough for any finiteness sweep to trip); non-float leaves and
    non-array values pass through untouched.  ``index`` (FaultSpec
    ``payload_index``) restricts the poison to one element of a
    tuple/list payload — the targeted-gradient fault the numerics
    plane's leaf attribution is tested with."""
    if payload is None:
        return None
    if isinstance(payload, (list, tuple)):
        if index is not None:
            out = list(payload)
            if not -len(out) <= index < len(out):
                raise IndexError(
                    f"chaos payload_index {index} out of range for a "
                    f"{len(out)}-element payload")
            out[index] = _poison(out[index])
            return type(payload)(out)
        return type(payload)(_poison(p) for p in payload)
    data = getattr(payload, "_data", None)       # paddle Tensor
    if data is not None:
        poisoned = _poison_array(data)
        if poisoned is data:
            return payload
        return type(payload)(poisoned)
    return _poison_array(payload)


def _poison_array(arr):
    try:
        a = np.asarray(arr)
    except Exception:                            # noqa: BLE001
        return arr
    if not np.issubdtype(a.dtype, np.floating):
        return arr
    a = a.copy()
    a.reshape(-1)[0] = np.nan
    return a


_registry = ChaosRegistry()
_env_armed = False
_explicit_seed = False


def arm_from_flags(force: bool = False):
    """Arm the registry from FLAGS_chaos_spec / FLAGS_chaos_seed (env or
    set_flags).  Called lazily on the first fault_point hit so a launcher
    can arm an entire child-process tree via the environment.  The env
    seed is applied only when no explicit reset(seed)/reseed happened
    first — lazy env arming must never clobber a seed the caller pinned
    (unless ``force=True`` re-reads the flags deliberately)."""
    global _env_armed
    if _env_armed and not force:
        return
    _env_armed = True
    from paddle_tpu.framework.flags import flag
    if force or not _explicit_seed:
        _registry.reseed(int(flag("chaos_seed")))
    raw = flag("chaos_spec")
    if not raw:
        return
    spec = json.loads(raw) if isinstance(raw, str) else dict(raw)
    for name, kw in spec.items():
        _registry.arm(name, **kw)


def fault_point(name: str, payload: Any = None, meta: dict = None):
    """Consult the chaos registry at a named site.  Returns the payload
    (possibly NaN-poisoned), raises :class:`InjectedFault`, or sleeps,
    per the armed schedule; a no-op returning ``payload`` when nothing
    is armed for ``name``."""
    if not _env_armed:
        arm_from_flags()
    if not _registry.armed:
        return payload
    return _registry.fire(name, payload, meta)


def arm(name: str, **spec) -> FaultSpec:
    if not _env_armed:
        arm_from_flags()
    return _registry.arm(name, **spec)


def disarm(name: Optional[str] = None):
    _registry.disarm(name)


def reset(seed: int = 0):
    """Disarm everything and reseed — each chaos test starts here."""
    global _explicit_seed
    _explicit_seed = True
    _registry.disarm()
    _registry.reseed(seed)


def stats() -> Dict[str, Dict[str, int]]:
    return _registry.stats()


def arm_state() -> Dict[str, Any]:
    """JSON-able snapshot of the full chaos state — seed, mid-sequence
    rng stream, and every armed spec with its call/trip counters.
    Recorded into incident bundles so :func:`restore_state` resumes the
    exact fault schedule a mid-run incident saw (the seed alone would
    replay from call zero, a different schedule)."""
    if not _env_armed:
        arm_from_flags()
    return _registry.export_state()


def restore_state(state: Dict[str, Any]):
    """Reinstall an :func:`arm_state` snapshot (replay's arming path).

    Pins the seed as explicit (lazy env arming must not clobber a
    restored stream) and auto-registers spec names this process has not
    declared — they were valid where the snapshot was taken, and a
    replay refusing its own recorded schedule would be the
    false-green the registry exists to prevent."""
    global _env_armed, _explicit_seed
    _env_armed = True
    _explicit_seed = True
    for name in dict(state.get("specs") or {}):
        if name not in _known_points:
            register_fault_point(name, carries_payload=True)
    _registry.import_state(state)


@contextlib.contextmanager
def inject(name: str, **spec):
    """Scope one armed fault point::

        with chaos.inject("ps.rpc", mode="error", nth=2, n_times=1):
            client.pull(...)     # the 2nd RPC raises InjectedFault
    """
    fs = arm(name, **spec)
    try:
        yield fs
    finally:
        disarm(name)
