"""SelectedRows — row-sparse gradients.

Reference: paddle/fluid/framework/selected_rows.h (rows_ + value_ +
height_), the merge-add in operators/math/selected_rows_functor.cc
(MergeAdd), and the sparse update modes of sgd_op.h / adam_op.h
(lazy_mode).  In the reference, lookup_table_op with is_sparse=True emits
a SelectedRows gradient so a trillion-row table never materialises a
dense grad.

TPU split: the *jitted* path never needs this (XLA fuses gather-grad
scatters, and giant tables live in the PS tier); SelectedRows serves the
*eager* tape, where a dense zeros(vocab, dim) per backward would bury
the host for large vocabularies.  ``Embedding(sparse=True)`` produces
one; optimizers apply row updates directly.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["SelectedRows"]


class SelectedRows:
    """rows [n] int64 ids into a height-row table + values [n, ...]."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = jnp.asarray(rows, jnp.int64).reshape(-1)
        self.values = jnp.asarray(values)
        self.height = int(height)

    # -- arithmetic the autograd engine needs -------------------------------
    def __add__(self, other):
        if isinstance(other, SelectedRows):
            if other.height != self.height:
                raise ValueError("SelectedRows height mismatch")
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]), self.height)
        # dense + sparse -> dense (reference: selected_rows_functor
        # SelectedRowsAddTensor)
        dense = jnp.asarray(other)
        return dense.at[self.rows].add(self.values.astype(dense.dtype))

    __radd__ = __add__

    def __mul__(self, scalar):
        return SelectedRows(self.rows, self.values * scalar, self.height)

    __rmul__ = __mul__

    def merge(self) -> "SelectedRows":
        """MergeAdd (selected_rows_functor.cc): unique rows, summed
        values — run before any optimizer update so duplicate ids in a
        batch accumulate once."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0],
                               fill_value=self.height)
        acc = jnp.zeros((uniq.shape[0],) + self.values.shape[1:],
                        self.values.dtype).at[inv].add(self.values)
        keep = uniq < self.height
        n = int(jnp.sum(keep))
        order = jnp.argsort(~keep)            # real rows first
        return SelectedRows(uniq[order][:n], acc[order][:n], self.height)

    def to_dense(self):
        out = jnp.zeros((self.height,) + self.values.shape[1:],
                        self.values.dtype)
        return out.at[self.rows].add(self.values)

    def numpy(self):
        return np.asarray(self.to_dense())

    @property
    def shape(self):
        return [self.height] + list(self.values.shape[1:])

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, "
                f"value_shape={tuple(self.values.shape)})")
