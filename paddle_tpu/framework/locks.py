"""Instrumented lock plane: named Lock/RLock wrappers + a runtime
lock-order watchdog.

The runtime has grown ~50 lock/thread sites across 20 modules, and the
two deadlocks already fixed by hand (FlightRecorder's SIGTERM
self-deadlock, CollectorServer.shutdown on a never-started server) are
exactly the bug class that only surfaces once the unlucky interleaving
lands in production.  This module is the *dynamic* half of the
concurrency plane — the static half is the PTA4xx pass family
(framework/analysis/concurrency.py), and the two validate each other:
the AST passes extract a whole-repo held-before graph from source, the
watchdog rebuilds the same graph from what actually ran, and both name
a cycle by the same lock names.

* :func:`lock` / :func:`rlock` — drop-in named replacements for
  ``threading.Lock()`` / ``threading.RLock()``.  Disarmed (the
  default), an acquisition costs one flag-dict lookup on top of the
  underlying primitive; the PS service, cluster collector, ingest
  pipeline, and elastic agent create their locks through these
  factories, so one env flag instruments a whole process tree.

* :class:`LockWatchdog` — armed via ``FLAGS_lock_watchdog``, it
  records each thread's acquisition order, maintains the global
  held-before graph (edge A→B = "B was acquired while A was held"),
  and on a cycle fires a ``locks.cycle`` flight event naming the cycle
  (once per distinct cycle).  A release that held the lock longer than
  ``FLAGS_lock_hold_warn_ms`` fires ``locks.long_hold``.  Metrics:
  ``lock_waits_total`` (contended acquisitions), ``lock_hold_ms``
  (hold-time histogram, per release), ``lock_cycles_total``,
  ``lock_watchdog_errors_total``.

**The watchdog never raises.**  Every observation runs behind the
``locks.observe`` chaos point and a swallow-and-count guard: an
injected (or real) failure inside the bookkeeping increments
``lock_watchdog_errors_total`` and the caller's acquire/release
proceeds untouched — the watcher must never deadlock or crash the
watched lock.  A per-thread reentrancy latch additionally keeps the
observation path from observing itself (flight/monitor internals take
their own plain locks).

Naming: lock names are a process-global namespace — every instance
created as ``locks.lock("ps.conn")`` is ONE node in the held-before
graph.  That is deliberate: lock *order* is a property of the code
path (the class), not of the instance, and it is what lets the static
passes and the runtime graph agree on identity.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from paddle_tpu.framework import monitor
from paddle_tpu.framework.flags import flag

__all__ = ["TrackedLock", "LockWatchdog", "lock", "rlock", "watchdog",
           "held_locks"]

monitor.describe("lock_waits_total",
                 "tracked-lock acquisitions that found the lock held "
                 "(contended) while the watchdog was armed")
monitor.describe("lock_hold_ms",
                 "tracked-lock hold time (ms) histogram, watchdog armed")
monitor.describe("lock_cycles_total",
                 "distinct lock-order cycles the runtime watchdog has "
                 "named (locks.cycle flight events)")
monitor.describe("lock_long_holds_total",
                 "tracked-lock releases past FLAGS_lock_hold_warn_ms")
monitor.describe("lock_watchdog_errors_total",
                 "watchdog observations swallowed (locks.observe chaos "
                 "trips and real bookkeeping failures) — the watched "
                 "lock proceeds untouched")


class LockWatchdog:
    """Process-wide held-before graph + per-thread acquisition stacks.

    All mutating entry points (:meth:`note_acquire`,
    :meth:`note_release`, :meth:`note_wait`) swallow every exception —
    see the module docstring.  Read surfaces (:meth:`graph`,
    :meth:`cycles`, :meth:`held`) are for tests/tools."""

    def __init__(self):
        # graph + cycle bookkeeping guarded by a PLAIN lock (the
        # watchdog must not watch itself)
        self._glock = threading.Lock()
        self._graph: Dict[str, Dict[str, Tuple[str, int]]] = {}
        self._cycles: List[List[str]] = []
        self._reported: Set[frozenset] = set()
        self._local = threading.local()
        self._seen: Set[str] = set()
        self.errors = 0

    # -- per-thread state ---------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _observing(self) -> bool:
        return getattr(self._local, "busy", False)

    # -- observation points (never raise) -----------------------------------
    def note_wait(self, name: str):
        try:
            if self._observing():
                return
            self._local.busy = True
            try:
                monitor.stat_add("lock_waits_total")
            finally:
                self._local.busy = False
        except Exception:                  # noqa: BLE001 — never raises
            self.errors += 1
            try:
                monitor.stat_add("lock_watchdog_errors_total")
            except Exception:              # noqa: BLE001
                pass

    def note_acquire(self, name: str):
        try:
            if self._observing():
                return
            self._local.busy = True
            try:
                from paddle_tpu.framework import chaos
                chaos.fault_point("locks.observe", meta={"lock": name})  # pta: disable=PTA301 (swallow-and-count by contract: the except below counts the trip into lock_watchdog_errors_total)
                stack = self._stack()
                held = [n for n, _, _ in stack]
                stack.append((name, time.perf_counter(),
                              name in held))
                self._seen.add(name)
                for h in held:
                    if h != name:
                        self._add_edge(h, name)
            finally:
                self._local.busy = False
        except Exception:                  # noqa: BLE001 — never raises
            self.errors += 1
            monitor.stat_add("lock_watchdog_errors_total")

    def note_release(self, name: str, emit: bool = True):
        try:
            # cheap bail BEFORE the latch: release calls this
            # unconditionally (so a flag flip mid-hold cannot leak a
            # stack entry into a bogus future edge), and a disarmed
            # process must pay only this getattr
            st = getattr(self._local, "stack", None)
            if not st:
                return
            if self._observing():
                return
            self._local.busy = True
            try:
                stack = st
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i][0] == name:
                        _, t0, reentrant = stack.pop(i)
                        if reentrant or not emit:
                            return         # inner RLock hold: outer owns
                        held_ms = (time.perf_counter() - t0) * 1e3
                        monitor.observe("lock_hold_ms", held_ms)
                        warn_ms = float(flag("lock_hold_warn_ms"))
                        if warn_ms > 0 and held_ms > warn_ms:
                            monitor.stat_add("lock_long_holds_total")
                            from paddle_tpu.framework.observability \
                                import flight
                            flight.record(
                                "locks.long_hold", severity="warn",
                                lock=name, held_ms=round(held_ms, 3),
                                warn_ms=warn_ms,
                                thread=threading.current_thread().name)
                        return
            finally:
                self._local.busy = False
        except Exception:                  # noqa: BLE001 — never raises
            self.errors += 1
            monitor.stat_add("lock_watchdog_errors_total")

    # -- held-before graph --------------------------------------------------
    def _add_edge(self, a: str, b: str):
        """Record "b acquired while a held"; on a NEW edge, check for a
        cycle through it and fire locks.cycle once per distinct cycle."""
        import traceback
        with self._glock:
            edges = self._graph.setdefault(a, {})
            if b in edges:
                return
            site = traceback.extract_stack(limit=8)
            caller = next(
                ((f.filename, f.lineno) for f in reversed(site)
                 if "framework/locks" not in f.filename.replace(
                     "\\", "/")), ("?", 0))
            edges[b] = (str(caller[0]), int(caller[1]))
            path = self._find_path(b, a)
            if path is None:
                return
            cycle = path + [b]             # a ... -> a closing through b
            key = frozenset(cycle)
            if key in self._reported:
                return
            self._reported.add(key)
            self._cycles.append(cycle)
        monitor.stat_add("lock_cycles_total")
        from paddle_tpu.framework.observability import flight
        flight.record("locks.cycle", severity="error", cycle=cycle,
                      edge=[a, b], site=f"{caller[0]}:{caller[1]}",
                      thread=threading.current_thread().name)

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src -> dst over the held-before edges (graph lock
        held by the caller)."""
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._graph.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- read surfaces ------------------------------------------------------
    def graph(self) -> Dict[str, List[str]]:
        """Held-before adjacency (name -> sorted successor names)."""
        with self._glock:
            return {a: sorted(bs) for a, bs in self._graph.items()}

    def cycles(self) -> List[List[str]]:
        with self._glock:
            return [list(c) for c in self._cycles]

    def held(self) -> List[str]:
        """Locks the CALLING thread currently holds, acquisition order."""
        return [n for n, _, _ in self._stack()]

    def seen(self) -> List[str]:
        """Every lock name observed since arming/reset, sorted — leaf
        locks included (the held-before graph only shows NESTED
        acquisitions; this answers "did the run exercise lock X at
        all", the adoption-coverage question)."""
        with self._glock:
            return sorted(self._seen)

    def reset(self):
        with self._glock:
            self._graph.clear()
            self._cycles.clear()
            self._reported.clear()
            self._seen.clear()
        self.errors = 0


#: process-wide watchdog every TrackedLock reports to
watchdog = LockWatchdog()


def held_locks() -> List[str]:
    """Tracked locks the calling thread holds (debug/test surface)."""
    return watchdog.held()


def _armed() -> bool:
    return bool(flag("lock_watchdog"))


class TrackedLock:
    """A named ``threading.Lock``/``RLock`` that reports to the
    watchdog when ``FLAGS_lock_watchdog`` is set.  Disarmed, acquire
    and release add one flag lookup each to the primitive's cost.
    Supports the full lock protocol (``with``, ``acquire(blocking,
    timeout)``, ``release``, ``locked``)."""

    __slots__ = ("name", "reentrant", "_lock")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = str(name)
        self.reentrant = bool(reentrant)
        self._lock = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _armed():
            return self._lock.acquire(blocking, timeout)
        got = self._lock.acquire(False)
        if not got:
            watchdog.note_wait(self.name)
            if not blocking:
                return False
            got = self._lock.acquire(True, timeout)
            if not got:
                return False
        watchdog.note_acquire(self.name)
        return True

    def release(self):
        # unconditional: a watchdog disarmed between acquire and
        # release must still reconcile the per-thread stack, or the
        # stale entry fabricates held-before edges (and spurious
        # locks.cycle events) once re-armed.  Metrics/events only emit
        # while armed; the disarmed no-stack path is one getattr.
        watchdog.note_release(self.name, emit=_armed())
        self._lock.release()

    def locked(self) -> bool:
        if self.reentrant:
            # RLock has no locked(); probe without blocking.  True when
            # ANOTHER thread holds it (an owning thread re-acquires).
            got = self._lock.acquire(False)
            if got:
                self._lock.release()
                return False
            return True
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self):
        kind = "rlock" if self.reentrant else "lock"
        return f"TrackedLock({self.name!r}, {kind})"

    # pickling (DataLoader spawn workers carry datasets by value): the
    # primitive is recreated unlocked in the child, same as a plain
    # threading lock field would have to be
    def __getstate__(self):
        return {"name": self.name, "reentrant": self.reentrant}

    def __setstate__(self, d):
        object.__setattr__(self, "name", d["name"])
        object.__setattr__(self, "reentrant", d["reentrant"])
        object.__setattr__(
            self, "_lock",
            threading.RLock() if d["reentrant"] else threading.Lock())


def lock(name: str) -> TrackedLock:
    """A named non-reentrant tracked lock (``threading.Lock`` drop-in)."""
    return TrackedLock(name, reentrant=False)


def rlock(name: str) -> TrackedLock:
    """A named reentrant tracked lock (``threading.RLock`` drop-in)."""
    return TrackedLock(name, reentrant=True)
