"""Auto-checkpoint / failure recovery — TrainEpochRange parity.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
(TrainEpochRange at :76, the ``for epoch in acp.train_epoch_range(N):``
loop protocol) — on every epoch boundary the trainer persists program
state + a status record; after a crash the relaunched job re-enters the
same loop and silently skips the epochs already done, restoring state.

TPU-native differences: state registration is explicit (a TrainStep or
{name: state_dict-able} objects) instead of scraped from a global
executor scope, storage is a local/NFS directory instead of HDFS, and
sharded pjit arrays go through paddle_tpu.distributed.checkpoint so each
host writes only its own shards.  Two checkpoint slots are alternated
(the reference's max_checkpoint_num=2 convention) so a crash mid-save
never corrupts the only copy.
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Iterator, Optional

from paddle_tpu.distributed import checkpoint as dckpt

__all__ = ["TrainEpochRange", "train_epoch_range", "latest_checkpoint"]

_STATUS = "acp_status.json"


def latest_checkpoint(checkpoint_dir: str, verify: bool = True):
    """The latest committed AND verified slot under a TrainEpochRange
    checkpoint directory: ``(slot_dir, epoch)``, or None when nothing
    restorable exists.  The status record is the two-slot protocol's
    commit point, so a mid-save (torn) slot is never returned — and
    since a disk can rot a slot AFTER its commit, the slot's shards are
    re-verified against their crc32 stamps here: a committed slot whose
    metadata parses but whose shard files are missing / truncated /
    bit-flipped fires ``ckpt.corrupt`` (inside verify) plus a
    ``ckpt.fallback`` flight event, and the walk falls back to the
    OTHER slot (the previous epoch, its own metadata supplying the
    epoch number) instead of surfacing a raw IO error deep in restore.
    This is what the elastic re-form path
    (paddle_tpu.distributed.elastic.reform) restores from when the job
    shrinks or grows."""
    try:
        with open(os.path.join(checkpoint_dir, _STATUS)) as f:
            s = json.load(f)
        slot_name, epoch = s["slot"], int(s["epoch"])
    except (OSError, ValueError, KeyError):
        return None
    candidates = [(os.path.join(checkpoint_dir, slot_name), epoch)]
    other = "slot1" if slot_name == "slot0" else "slot0"
    other_dir = os.path.join(checkpoint_dir, other)
    try:
        candidates.append((other_dir,
                           int(dckpt.checkpoint_meta(other_dir)["step"])))
    except (OSError, ValueError, KeyError, TypeError):
        pass                       # no usable second slot: one candidate
    for slot_dir, ep in candidates:
        if not verify:
            return slot_dir, ep
        problems = dckpt.verify_checkpoint(slot_dir)
        if not problems:
            return slot_dir, ep
        from paddle_tpu.framework import monitor
        from paddle_tpu.framework.observability import flight
        monitor.stat_add("ckpt_fallback_total")
        flight.record("ckpt.fallback", severity="warn", dir=slot_dir,
                      epoch=ep,
                      reasons=sorted({p["reason"] for p in problems}))
    return None


class TrainEpochRange:
    """Crash-resumable epoch iterator around a (Sharded)TrainStep.

    Usage::

        r = TrainEpochRange(max_epoch_num=10, name="job0",
                            train_step=step, checkpoint_dir=path)
        for epoch in r:
            ... train one epoch with `step` ...

    After a restart, epochs already checkpointed are skipped and the
    step's params/opt/buffers are restored before the first yielded epoch.
    """

    def __init__(self, max_epoch_num: int, name: str, train_step=None,
                 checkpoint_dir: Optional[str] = None,
                 save_checkpoint_inter: float = 0.0,
                 world_size: Optional[int] = None):
        self.max_epoch_num = max_epoch_num
        self.name = name
        self.train_step = train_step
        self.save_checkpoint_inter = save_checkpoint_inter
        self.world_size = world_size
        self.checkpoint_dir = checkpoint_dir or os.environ.get(
            "PADDLE_CHECKPOINT_DIR", os.path.join(".acp", name))
        self._last_save = 0.0
        self.restored_epoch = -1
        status = self._read_status()
        if status is not None and train_step is not None:
            # verified slot walk: a committed slot that rotted on disk
            # falls back to the other slot's epoch instead of crashing
            found = latest_checkpoint(self.checkpoint_dir)
            if found is not None:
                slot, epoch = found
                dckpt.load_train_state(train_step, slot)
                self.restored_epoch = int(epoch)

    # -- status record ------------------------------------------------------
    def _status_path(self):
        return os.path.join(self.checkpoint_dir, _STATUS)

    def _read_status(self):
        try:
            with open(self._status_path()) as f:
                s = json.load(f)
            return s if s.get("name") == self.name else None
        except (OSError, ValueError):
            return None

    def _write_status(self, epoch: int, slot: str):
        # atomic flip = commit point; routed through the chaos-instrumented
        # crash-safe writer so kill-mid-commit is injectable (fs.write)
        from paddle_tpu.distributed.fleet.utils.fs import LocalFS
        LocalFS().atomic_write(
            self._status_path(),
            json.dumps({"name": self.name, "epoch": epoch, "slot": slot,
                        "time": time.time()}))

    # -- save ---------------------------------------------------------------
    def save_checkpoint(self, epoch: int):
        """Persist state for ``epoch`` into the inactive slot, then commit
        by atomically flipping the status record."""
        if self.train_step is None:
            return
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        status = self._read_status()
        slot = "slot1" if (status and status.get("slot") == "slot0") \
            else "slot0"
        slot_dir = os.path.join(self.checkpoint_dir, slot)
        if os.path.isdir(slot_dir):
            shutil.rmtree(slot_dir)
        dckpt.save_train_state(self.train_step, slot_dir, global_step=epoch,
                               world_size=self.world_size)
        # verify-before-flip: the status record must never point at a
        # slot that can't be read back — a failed verify leaves the old
        # status (and the old slot) standing
        problems = dckpt.verify_checkpoint(slot_dir)
        if problems:
            raise dckpt.CheckpointVerifyError(
                f"refusing to commit {slot_dir}: "
                + "; ".join(f"{p['file']}: {p['reason']}"
                            for p in problems[:4]))
        self._write_status(epoch, slot)
        self._last_save = time.monotonic()

    # -- iteration ----------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        start = self.restored_epoch + 1
        for epoch in range(start, self.max_epoch_num):
            yield epoch
            now = time.monotonic()
            if (self.save_checkpoint_inter <= 0 or
                    now - self._last_save >= self.save_checkpoint_inter or
                    epoch == self.max_epoch_num - 1):
                self.save_checkpoint(epoch)
        self._append_run_record(start)

    def _append_run_record(self, start_epoch: int):
        """A completed epoch range appends one ``train_epoch``
        RunRecord to the persistent run ledger when FLAGS_runlog_dir
        arms the observatory (empty flag = off, zero cost).
        Best-effort by contract: the ledger must never fail the
        training loop it records."""
        try:
            from paddle_tpu.framework import runlog
            path = runlog.default_ledger_path()
            if not path:
                return
            rec = runlog.capture(
                "train_epoch", label=self.name,
                extra={"epochs": {"start": start_epoch,
                                  "end": self.max_epoch_num - 1,
                                  "restored": self.restored_epoch}})
            runlog.RunLedger(path).append(rec)
        except Exception:          # noqa: BLE001 — recorder never crashes
            pass


def train_epoch_range(max_epoch_num: int, name: str = "default",
                      train_step=None, checkpoint_dir: Optional[str] = None,
                      save_checkpoint_inter: float = 0.0):
    """Functional form matching ``acp.train_epoch_range(N, inter)``
    (auto_checkpoint.py:676)."""
    return iter(TrainEpochRange(max_epoch_num, name, train_step,
                                checkpoint_dir, save_checkpoint_inter))
