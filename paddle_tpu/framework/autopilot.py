"""Autopilot: a guarded runtime controller that turns telemetry into
recovery actions.

Every earlier plane ends at a signal — a flight event, a health
anomaly, a blame share, a straggler score — and a human is the only
actuator.  This module closes the loop: a :class:`Controller` ticks
once per train step, and every ``FLAGS_autopilot_interval_steps`` steps
it collects an interval snapshot of the signal planes and sweeps a
declarative **policy table** mapping conditions onto a bounded
**actuator registry**:

======================  =================================================
actuator                effect
======================  =================================================
``prefetch.deepen``     ``PSTrainStep.set_prefetch_depth(depth+1)`` (cap
                        ``FLAGS_autopilot_max_prefetch_depth``) — hide PS
                        pull latency behind more in-flight windows
``prefetch.shallow``    the deepen's revert: restore the previous depth
``wire.retreat``        ``PsClient.set_wire_dtype("f32")`` — numerics
                        trouble trumps bandwidth; re-handshakes per server
``wire.advance``        ``PsClient.set_wire_dtype("bf16")`` — bandwidth
                        blame with clean numerics earns compression back
``scaler.tighten``      ``GradScaler.tighten_growth()`` — grow the loss
                        scale 4x more slowly after a scale collapse
``resilient.restore``   ``ResilientTrainStep.restore()`` + streak reset —
                        force a known-good snapshot when non-finite
                        streaks exceed budget (no revert: a restore is
                        not undoable and needs none)
``elastic.shrink``      ``ElasticAgent.enforce_straggler_policy()`` —
                        deadline-guarded replace/shrink of a persistent
                        straggler (no revert: membership moves forward)
======================  =================================================

Robustness is the design center, not a feature:

* **hysteresis** — a policy must fire on ``FLAGS_autopilot_hysteresis``
  *consecutive* evaluation intervals before its action runs; one noisy
  interval moves nothing.
* **cooldown** — an action just taken cannot re-fire for
  ``FLAGS_autopilot_cooldown_s`` seconds (the controller's clock, which
  is injectable — tests drive it deterministically).
* **global budget** — at most ``FLAGS_autopilot_max_actions`` actions
  per ``FLAGS_autopilot_window_s`` rolling window, across all policies.
* **dry-run** — ``FLAGS_autopilot_dry_run`` computes and records every
  decision but applies nothing: the training trajectory is bitwise
  identical to autopilot-off.
* **guarded rollback** — each applied action snapshots the objective
  (interval mean step ms + bad-event count); after
  ``FLAGS_autopilot_rollback_intervals`` further evaluations the
  controller re-measures, and an action that made things worse (step
  time regressed beyond ``FLAGS_autopilot_rollback_tolerance``, or bad
  events increased) is reverted with the state its actuator returned.
* **the watcher never crashes the watched** — every actuator
  application passes the ``autopilot.act`` chaos point and every
  exception (injected or real) degrades to a counted flight event
  (``autopilot_act_errors_total``), never an exception in the train
  loop.

Every decision — taken, suppressed, reverted, errored — is a flight
event (``autopilot.action`` / ``.suppressed`` / ``.revert`` /
``.act_error``) and, when a :class:`~paddle_tpu.framework.runlog.RunLedger`
is attached, a ``kind="autopilot"`` ledger record (empty ``summary`` —
``perf_report compare`` sees the audit trail but never mistakes it for
a measured run).

Threading contract: ``tick``/``evaluate`` run on the train-loop thread
only (same single-consumer contract as ``PSTrainStep``'s prefetch
pipeline); the signal planes it reads are each internally thread-safe.

The second, offline half lives in ``tools/autotune.py``: it replays the
run ledger to search the same knob space against a measured objective
and emits a tuned profile that :func:`maybe_apply_tuned_profile` (called
from ``TrainStep``/``PSTrainStep`` construction and ``bench.py``
startup) turns into flag overrides — the runtime controller starts from
a tuned operating point instead of defaults.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional

from paddle_tpu.framework import chaos, monitor
from paddle_tpu.framework.flags import flag, set_flags
from paddle_tpu.framework.observability import flight

__all__ = [
    "Policy", "Actuator", "Controller", "default_policies",
    "default_actuators", "attach", "load_tuned_profile",
    "maybe_apply_tuned_profile",
]

# policy thresholds (per evaluation interval).  Absolute floors matter:
# on a clean localhost run ps_wait can dominate the *share* of a
# microsecond-scale step, and a share-only trigger would deepen
# prefetch on a run with nothing to hide.
PS_WAIT_MIN_MS = 20.0        # per-step ps_wait ms before prefetch acts
PS_WAIT_MIN_SHARE = 0.4      # ...and its share of the step
WIRE_ADVANCE_MIN_SHARE = 0.5  # stricter: advance re-risks numerics
NAN_SKIPS_RETREAT = 2        # nan skips per interval → wire retreat
CONSECUTIVE_BAD_RESTORE = 2  # non-finite streak → forced restore


class Policy:
    """One row of the policy table: ``when(signals)`` returns a reason
    string when the condition holds this interval (``None`` otherwise);
    ``action`` names the actuator to run after ``hysteresis``
    consecutive confirming intervals (``None`` → controller default)."""

    def __init__(self, name: str, action: str,
                 when: Callable[[Dict[str, Any]], Optional[str]],
                 hysteresis: Optional[int] = None):
        self.name = name
        self.action = action
        self.when = when
        self.hysteresis = hysteresis


class Actuator:
    """One registry entry: ``apply(ctl)`` mutates the target and
    returns the state ``revert(ctl, state)`` needs to undo it
    (``revert=None`` → the rollback guard skips this action);
    ``available(ctl)`` gates policies whose target isn't attached."""

    def __init__(self, name: str,
                 apply: Callable[["Controller"], Any],
                 revert: Optional[Callable[["Controller", Any], None]] = None,
                 available: Optional[Callable[["Controller"], bool]] = None):
        self.name = name
        self.apply = apply
        self.revert = revert
        self._available = available

    def available(self, ctl: "Controller") -> bool:
        return True if self._available is None else bool(
            self._available(ctl))


# -- signal helpers (pure functions of the signals dict) -----------------

def _blame_ms(sig: Dict[str, Any], cat: str) -> float:
    return float((sig.get("blame_per_step") or {}).get(cat, 0.0))


def _blame_share(sig: Dict[str, Any], cat: str) -> float:
    b = sig.get("blame_per_step") or {}
    tot = sum(b.values())
    return float(b.get(cat, 0.0)) / tot if tot > 0 else 0.0


def _numerics_trouble(sig: Dict[str, Any]) -> bool:
    return bool(sig.get("scale_collapses", 0) or sig.get("nan_skips", 0)
                or sig.get("consecutive_bad", 0))


def default_policies() -> List[Policy]:
    """The built-in policy table (see module docstring for the actuator
    each row drives)."""

    def p_deepen(s):
        w, sh = _blame_ms(s, "ps_wait"), _blame_share(s, "ps_wait")
        if w >= PS_WAIT_MIN_MS and sh >= PS_WAIT_MIN_SHARE:
            return f"ps_wait {w:.1f}ms/step, {sh:.0%} of step"
        return None

    def p_retreat(s):
        if s.get("wire_dtype") in (None, "f32"):
            return None
        if s.get("scale_collapses", 0):
            return f"{s['scale_collapses']} scale collapse(s) on bf16 wire"
        if s.get("nan_skips", 0) >= NAN_SKIPS_RETREAT:
            return f"{s['nan_skips']} nan skips on bf16 wire"
        return None

    def p_advance(s):
        if s.get("wire_dtype") != "f32" or _numerics_trouble(s):
            return None
        w, sh = _blame_ms(s, "ps_wait"), _blame_share(s, "ps_wait")
        if w >= PS_WAIT_MIN_MS and sh >= WIRE_ADVANCE_MIN_SHARE:
            return (f"ps_wait {w:.1f}ms/step, {sh:.0%} of step, "
                    f"numerics clean")
        return None

    def p_tighten(s):
        if s.get("scale_collapses", 0):
            return f"{s['scale_collapses']} scale collapse(s)"
        return None

    def p_restore(s):
        cb = s.get("consecutive_bad", 0)
        if cb >= CONSECUTIVE_BAD_RESTORE:
            return f"non-finite streak {cb}"
        return None

    def p_shrink(s):
        over = s.get("stragglers_overdue") or []
        if over:
            return "straggler(s) past deadline: " + ",".join(over)
        return None

    return [
        # numerics first: a retreat must win the interval over an
        # advance, and a restore must beat bandwidth tuning
        Policy("wire.retreat", "wire.retreat", p_retreat, hysteresis=1),
        Policy("scaler.tighten", "scaler.tighten", p_tighten,
               hysteresis=1),
        Policy("resilient.restore", "resilient.restore", p_restore,
               hysteresis=1),
        Policy("prefetch.deepen", "prefetch.deepen", p_deepen),
        Policy("wire.advance", "wire.advance", p_advance, hysteresis=3),
        Policy("elastic.shrink", "elastic.shrink", p_shrink,
               hysteresis=1),
    ]


# -- actuator implementations -------------------------------------------

def _act_deepen(ctl):
    prev = ctl.step.set_prefetch_depth(
        min(ctl.step.prefetch_depth + 1, ctl.max_prefetch_depth))
    return {"prefetch_depth": prev}


def _act_deepen_revert(ctl, state):
    ctl.step.set_prefetch_depth(int(state["prefetch_depth"]))


def _act_wire(to):
    def apply(ctl):
        return {"wire_dtype": ctl.client().set_wire_dtype(to)}
    return apply


def _act_wire_revert(ctl, state):
    ctl.client().set_wire_dtype(state["wire_dtype"])


def _act_tighten(ctl):
    return ctl.scaler.tighten_growth()


def _act_tighten_revert(ctl, state):
    ctl.scaler.restore_growth(state)


def _act_restore(ctl):
    ctl.resilient.restore()
    # restore() alone leaves the streak counting toward train.abort;
    # the forced rollback IS the recovery, so the streak restarts
    ctl.resilient.consecutive_bad = 0
    return None


def _act_shrink(ctl):
    ctl.agent.enforce_straggler_policy(ctl.straggler_deadline)
    return None


def default_actuators() -> Dict[str, Actuator]:
    can_prefetch = lambda c: c.step is not None and \
        hasattr(c.step, "set_prefetch_depth")            # noqa: E731
    can_wire = lambda c: c.client() is not None          # noqa: E731
    return {
        "prefetch.deepen": Actuator(
            "prefetch.deepen", _act_deepen, _act_deepen_revert,
            available=lambda c: can_prefetch(c) and
            c.step.prefetch_depth < c.max_prefetch_depth),
        "prefetch.shallow": Actuator(
            "prefetch.shallow",
            lambda c: {"prefetch_depth": c.step.set_prefetch_depth(
                max(0, c.step.prefetch_depth - 1))},
            _act_deepen_revert, available=can_prefetch),
        "wire.retreat": Actuator(
            "wire.retreat", _act_wire("f32"), _act_wire_revert,
            available=can_wire),
        "wire.advance": Actuator(
            "wire.advance", _act_wire("bf16"), _act_wire_revert,
            available=can_wire),
        "scaler.tighten": Actuator(
            "scaler.tighten", _act_tighten, _act_tighten_revert,
            available=lambda c: c.scaler is not None),
        "resilient.restore": Actuator(
            "resilient.restore", _act_restore, None,
            available=lambda c: c.resilient is not None),
        "elastic.shrink": Actuator(
            "elastic.shrink", _act_shrink, None,
            available=lambda c: c.agent is not None),
    }


class Controller:
    """The runtime half of the autopilot (see module docstring).

    Targets are attached explicitly — ``step`` (a ``PSTrainStep``, or
    anything with ``prefetch_depth``/``set_prefetch_depth``),
    ``client`` (``PsClient``; resolved from
    ``step.embedding.table.client`` when omitted), ``scaler``
    (``GradScaler``), ``resilient`` (``ResilientTrainStep``), ``agent``
    (``ElasticAgent``) — and a missing target simply disables the
    policies that would drive it.  ``blame_source`` is a zero-arg
    callable returning a ``framework.blame`` result (or its
    ``summary()`` dict) for the *current* interval; alternatively push
    one with :meth:`note_blame`.  ``ledger`` is a
    ``runlog.RunLedger`` the audit records append to.

    All decision state is private to the train-loop thread; ``clock``
    (default ``time.monotonic``) is the ONLY time source decisions
    consult, so a test driving a fake clock replays bit-identically.
    """

    def __init__(self, *, step=None, client=None, scaler=None,
                 resilient=None, agent=None,
                 blame_source: Optional[Callable[[], dict]] = None,
                 ledger=None,
                 policies: Optional[List[Policy]] = None,
                 actuators: Optional[Dict[str, Actuator]] = None,
                 clock: Optional[Callable[[], float]] = None,
                 dry_run: Optional[bool] = None,
                 interval_steps: Optional[int] = None,
                 hysteresis: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 max_actions: Optional[int] = None,
                 window_s: Optional[float] = None,
                 rollback_intervals: Optional[int] = None,
                 rollback_tolerance: Optional[float] = None,
                 max_prefetch_depth: Optional[int] = None,
                 straggler_deadline: Optional[float] = None):
        self.step = step
        self._client = client
        self.scaler = scaler
        self.resilient = resilient
        self.agent = agent
        self.blame_source = blame_source
        self.ledger = ledger
        self.policies = default_policies() if policies is None \
            else list(policies)
        self.actuators = default_actuators() if actuators is None \
            else dict(actuators)
        self.clock = clock or time.monotonic

        def _f(v, name, cast):
            return cast(flag(name)) if v is None else cast(v)
        self.dry_run = _f(dry_run, "autopilot_dry_run", bool)
        self.interval_steps = max(1, _f(
            interval_steps, "autopilot_interval_steps", int))
        self.hysteresis = max(1, _f(
            hysteresis, "autopilot_hysteresis", int))
        self.cooldown_s = _f(cooldown_s, "autopilot_cooldown_s", float)
        self.max_actions = _f(max_actions, "autopilot_max_actions", int)
        self.window_s = _f(window_s, "autopilot_window_s", float)
        self.rollback_intervals = max(1, _f(
            rollback_intervals, "autopilot_rollback_intervals", int))
        self.rollback_tolerance = _f(
            rollback_tolerance, "autopilot_rollback_tolerance", float)
        self.max_prefetch_depth = _f(
            max_prefetch_depth, "autopilot_max_prefetch_depth", int)
        self.straggler_deadline = _f(
            straggler_deadline, "autopilot_straggler_deadline", float)

        # decision state (train-loop thread only)
        self._ticks = 0
        self._evals = 0
        self._streak: Dict[str, int] = {}
        self._last_action_t: Dict[str, float] = {}
        self._action_times: List[float] = []
        self._pending: List[dict] = []       # applied, awaiting verdict
        self._noted_blame: Optional[dict] = None
        # interval baselines for cumulative counters/histograms
        self._prev_totals: Dict[str, int] = dict(flight.kind_totals())
        self._prev_hist: Dict[str, tuple] = {}
        self._prev_stats: Dict[str, float] = {}
        self._prime_counters()
        self.decisions: List[dict] = []      # full audit, test-readable

    # -- target resolution ----------------------------------------------
    def client(self):
        """The PS client actuators flip: the explicit one, else the one
        behind ``step.embedding.table.client``."""
        if self._client is not None:
            return self._client
        table = getattr(getattr(self.step, "embedding", None),
                        "table", None)
        return getattr(table, "client", None)

    def note_blame(self, result: Optional[dict]):
        """Push this interval's blame (a ``compute_blame`` result or a
        ``blame.summary()`` dict); consumed by the next evaluation."""
        self._noted_blame = result

    # -- signal collection ----------------------------------------------
    def _prime_counters(self):
        for name in ("health_anomalies_total",):
            self._prev_stats[name] = float(
                monitor.get_stat(name) or 0)
        for hname, h in monitor.all_histograms().items():
            self._prev_hist[hname] = (h["count"], h["sum"]) \
                if isinstance(h, dict) else (h.count, h.sum)

    def _hist_delta(self, name: str) -> tuple:
        h = monitor.get_histogram(name)
        pc, ps = self._prev_hist.get(name, (0, 0.0))
        d = (h.count - pc, h.sum - ps)
        self._prev_hist[name] = (h.count, h.sum)
        return d

    def _stat_delta(self, name: str) -> float:
        cur = float(monitor.get_stat(name) or 0)
        d = cur - self._prev_stats.get(name, 0.0)
        self._prev_stats[name] = cur
        return d

    def _flight_delta(self, kind: str, totals: Dict[str, int]) -> int:
        d = totals.get(kind, 0) - self._prev_totals.get(kind, 0)
        return d

    @staticmethod
    def _norm_blame(raw: Optional[dict]) -> Dict[str, float]:
        """Either shape → per-step ms by category."""
        if not raw:
            return {}
        if "per_step_ms" in raw:             # compute_blame result
            return {k: float(v) for k, v in raw["per_step_ms"].items()}
        out = {}
        for k, v in raw.items():             # summary() dict
            if k.startswith("blame_") and k.endswith("_ms"):
                out[k[len("blame_"):-len("_ms")]] = float(v)
        return out

    def _collect(self) -> Dict[str, Any]:
        totals = dict(flight.kind_totals())
        sc, ss = self._hist_delta("train_step_ms")
        rpc_c, rpc_s = 0, 0.0
        for name in monitor.all_histograms():
            if name.startswith("ps_client_rpc_ms_"):
                c, s = self._hist_delta(name)
                rpc_c += c
                rpc_s += s
        blame_raw = self._noted_blame
        self._noted_blame = None
        if self.blame_source is not None:
            try:
                blame_raw = self.blame_source()
            except Exception:                # noqa: BLE001 — a broken
                monitor.stat_add(           # signal plane must not
                    "autopilot_signal_errors_total")  # stop the sweep
                blame_raw = None
        sig = {
            "steps": sc,
            "step_ms": (ss / sc) if sc else None,
            "rpc_ms": (rpc_s / rpc_c) if rpc_c else None,
            "rpc_count": rpc_c,
            "anomalies": self._stat_delta("health_anomalies_total"),
            "scale_collapses": self._flight_delta(
                "numerics.scale_collapse", totals),
            "nan_skips": self._flight_delta("train.nan_skip", totals),
            "consecutive_bad": getattr(
                self.resilient, "consecutive_bad", 0),
            "blame_per_step": self._norm_blame(blame_raw),
            "wire_dtype": getattr(self.client(), "wire_dtype", None),
            "prefetch_depth": getattr(self.step, "prefetch_depth",
                                      None),
            "stragglers_overdue": (
                self.agent.straggler_overdue(self.straggler_deadline)
                if self.agent is not None else []),
        }
        self._prev_totals = totals
        return sig

    # -- objective / rollback guard --------------------------------------
    @staticmethod
    def _objective(sig: Dict[str, Any]) -> tuple:
        bad = (sig.get("scale_collapses", 0) + sig.get("nan_skips", 0)
               + (1 if sig.get("consecutive_bad", 0) else 0))
        return (sig.get("step_ms"), bad)

    def _worse(self, base: tuple, sig: Dict[str, Any]) -> bool:
        b_ms, b_bad = base
        c_ms, c_bad = self._objective(sig)
        if c_bad > b_bad:
            return True
        return (b_ms is not None and c_ms is not None and
                c_ms > b_ms * (1.0 + self.rollback_tolerance))

    # -- driving ----------------------------------------------------------
    def tick(self) -> List[dict]:
        """Call once per train step; runs :meth:`evaluate` every
        ``interval_steps`` ticks.  Returns the decisions made this
        tick (usually ``[]``)."""
        self._ticks += 1
        if self._ticks % self.interval_steps == 0:
            return self.evaluate()
        return []

    def evaluate(self) -> List[dict]:
        """One evaluation interval: collect signals, settle pending
        rollback verdicts, sweep the policy table.  Never raises."""
        now = self.clock()
        self._evals += 1
        sig = self._collect()
        decisions: List[dict] = []

        # 1. verdicts on previously applied actions
        for p in list(self._pending):
            p["evals_left"] -= 1
            if p["evals_left"] > 0:
                continue
            self._pending.remove(p)
            if self._worse(p["baseline"], sig):
                self._revert(p, decisions)

        # 2. policy sweep
        for pol in self.policies:
            act = self.actuators.get(pol.action)
            if act is None or not act.available(self):
                self._streak[pol.name] = 0
                continue
            try:
                reason = pol.when(sig)
            except Exception:                # noqa: BLE001 — one bad
                monitor.stat_add(           # policy must not stop the
                    "autopilot_signal_errors_total")   # sweep
                reason = None
            if not reason:
                self._streak[pol.name] = 0
                continue
            streak = self._streak.get(pol.name, 0) + 1
            self._streak[pol.name] = streak
            need = self.hysteresis if pol.hysteresis is None \
                else pol.hysteresis
            if streak < need:
                self._suppress(pol, f"hysteresis {streak}/{need}",
                               reason, decisions)
                continue
            last = self._last_action_t.get(pol.action)
            if last is not None and now - last < self.cooldown_s:
                self._suppress(
                    pol, f"cooldown {now - last:.0f}s/"
                    f"{self.cooldown_s:.0f}s", reason, decisions)
                continue
            self._action_times = [t for t in self._action_times
                                  if now - t <= self.window_s]
            if len(self._action_times) >= self.max_actions:
                self._suppress(
                    pol, f"budget {len(self._action_times)}/"
                    f"{self.max_actions} per {self.window_s:.0f}s",
                    reason, decisions)
                continue
            self._take(pol, act, reason, sig, now, decisions)

        self.decisions.extend(decisions)
        return decisions

    # -- decision recording ----------------------------------------------
    def _decision(self, kind: str, policy: str, action: str,
                  reason: str) -> dict:
        return {"eval": self._evals, "step": self._ticks,
                "policy": policy, "action": action, "kind": kind,
                "reason": reason, "dry_run": self.dry_run}

    def _record(self, d: dict, severity: str):
        ev_kind = {"taken": "autopilot.action",
                   "suppressed": "autopilot.suppressed",
                   "reverted": "autopilot.revert",
                   "error": "autopilot.act_error"}[d["kind"]]
        # "kind" is the flight event's own field; the decision kind
        # travels as "decision"
        attrs = {("decision" if k == "kind" else k): v
                 for k, v in d.items()}
        flight.record(ev_kind, severity=severity, **attrs)
        if self.ledger is not None:
            from paddle_tpu.framework import runlog
            self.ledger.append({
                "schema_version": runlog.SCHEMA_VERSION,
                "kind": "autopilot", "label": d["policy"],
                "run_id": runlog._run_id(), "ts": time.time(),
                "meta": runlog.run_meta(),
                # empty summary: perf_report series see no signals in
                # these records, so the audit trail never perturbs a
                # compare over the same ledger
                "summary": {}, "action": dict(d)})

    def _suppress(self, pol: Policy, why: str, reason: str,
                  decisions: List[dict]):
        d = self._decision("suppressed", pol.name, pol.action,
                           f"{reason}; {why}")
        monitor.stat_add("autopilot_suppressed_total")
        self._record(d, "info")
        decisions.append(d)

    def _take(self, pol: Policy, act: Actuator, reason: str,
              sig: Dict[str, Any], now: float, decisions: List[dict]):
        self._streak[pol.name] = 0
        # dry-run books the action too: the decision SEQUENCE (with
        # cooldowns and budget) matches what a live run would do
        self._last_action_t[pol.action] = now
        self._action_times.append(now)
        d = self._decision("taken", pol.name, pol.action, reason)
        if self.dry_run:
            monitor.stat_add("autopilot_actions_total")
            self._record(d, "info")
            decisions.append(d)
            return
        try:
            chaos.fault_point("autopilot.act",  # pta: disable=PTA301 (fire-and-forget by contract: a faulting actuator degrades to the counted act_error path below, never a crashed train loop)
                              meta={"action": pol.action})
            state = act.apply(self)
        except Exception as e:               # noqa: BLE001 — the
            # watcher never crashes the watched: injected or real,
            # an actuator fault is a counted event, not an exception
            # in the train loop
            monitor.stat_add("autopilot_act_errors_total")
            d["kind"] = "error"
            d["error"] = f"{type(e).__name__}: {e}"
            self._record(d, "error")
            decisions.append(d)
            return
        monitor.stat_add("autopilot_actions_total")
        if act.revert is not None:
            self._pending.append({
                "decision": d, "state": state,
                "baseline": self._objective(sig),
                "evals_left": self.rollback_intervals})
        self._record(d, "warn" if pol.name.startswith(
            ("wire.retreat", "scaler", "resilient", "elastic"))
            else "info")
        decisions.append(d)

    def _revert(self, pending: dict, decisions: List[dict]):
        src = pending["decision"]
        act = self.actuators.get(src["action"])
        d = self._decision(
            "reverted", src["policy"], src["action"],
            f"objective worsened after {src['action']} "
            f"(taken at eval {src['eval']})")
        try:
            chaos.fault_point("autopilot.act",  # pta: disable=PTA301 (same contract as the apply site)
                              meta={"action": src["action"],
                                    "revert": True})
            act.revert(self, pending["state"])
        except Exception as e:               # noqa: BLE001 — same
            monitor.stat_add("autopilot_act_errors_total")  # contract
            d["kind"] = "error"              # as apply
            d["error"] = f"{type(e).__name__}: {e}"
            self._record(d, "error")
            decisions.append(d)
            return
        monitor.stat_add("autopilot_reverts_total")
        self._record(d, "warn")
        decisions.append(d)

    def snapshot(self) -> dict:
        """Controller state for reports/tests: counts by decision kind
        plus the knobs currently in force."""
        kinds: Dict[str, int] = {}
        for d in self.decisions:
            kinds[d["kind"]] = kinds.get(d["kind"], 0) + 1
        return {"ticks": self._ticks, "evals": self._evals,
                "decisions": kinds, "pending": len(self._pending),
                "dry_run": self.dry_run,
                "prefetch_depth": getattr(self.step, "prefetch_depth",
                                          None),
                "wire_dtype": getattr(self.client(), "wire_dtype",
                                      None)}


def attach(**targets) -> Optional[Controller]:
    """Build a :class:`Controller` over ``targets`` when
    ``FLAGS_autopilot`` is set; ``None`` (autopilot off) otherwise.
    The train loop calls ``ctl.tick()`` per step if non-``None``."""
    if not flag("autopilot"):
        return None
    return Controller(**targets)


# -- tuned startup profile (the offline half's output) -------------------

_applied_profiles: set = set()


def load_tuned_profile(path: str) -> dict:
    """Read + validate a ``tools/autotune.py`` profile: a JSON object
    ``{"schema_version": 1, "objective": ..., "knobs": {...}}``.
    Raises on malformed input — callers that must not raise go through
    :func:`maybe_apply_tuned_profile`."""
    with open(path, "r", encoding="utf-8") as f:
        prof = json.load(f)
    if not isinstance(prof, dict) or \
            not isinstance(prof.get("knobs"), dict):
        raise ValueError(f"not a tuned profile: {path}")
    if int(prof.get("schema_version", 0)) != 1:
        raise ValueError(
            f"unknown profile schema {prof.get('schema_version')!r}")
    return prof


def maybe_apply_tuned_profile(source: str = "") -> Optional[dict]:
    """Apply ``FLAGS_autotune_profile`` (if set) exactly once per
    process: translate its knobs into flag overrides (ps_prefetch_depth,
    ps_wire_dtype, zero_wire_dtype) via ``set_flags`` so every
    construction that follows starts from the tuned operating point.
    ``source`` labels the call site in the flight event.  Never raises:
    a missing/corrupt profile degrades to a counted
    ``autopilot.profile_error`` flight event and default knobs."""
    path = str(flag("autotune_profile") or "")
    if not path or path in _applied_profiles:
        return None
    _applied_profiles.add(path)
    try:
        prof = load_tuned_profile(path)
        knobs = prof["knobs"]
        updates: Dict[str, Any] = {}
        if "prefetch_depth" in knobs:
            updates["ps_prefetch_depth"] = int(knobs["prefetch_depth"])
        if "wire_dtype" in knobs:
            wd = str(knobs["wire_dtype"])
            updates["ps_wire_dtype"] = wd
            updates["zero_wire_dtype"] = wd
        # batch_size is consumed by the harness (bench reads the knob
        # itself) — flags carry no batch size to override here
        if updates:
            set_flags(updates)
        flight.record("autopilot.profile_applied", severity="info",
                      path=path, source=source,
                      knobs={k: knobs[k] for k in sorted(knobs)})
        return prof
    except Exception as e:                   # noqa: BLE001 — startup
        # tuning is advisory: a bad profile must never stop training
        monitor.stat_add("autopilot_profile_errors_total")
        flight.record("autopilot.profile_error", severity="warn",
                      path=path, source=source,
                      error=f"{type(e).__name__}: {e}")
        return None
