"""paddle.distribution parity — Uniform / Normal / Categorical.

Reference: python/paddle/distribution.py (Distribution base at :41,
Uniform :168, Normal :390, Categorical :640).  TPU-native notes: sampling
draws keys from the global Generator (tensor/random.py) so distributions
compose with paddle.seed and with jit key-threading; math is pure jnp and
fully differentiable through the tape (reparameterised samples for
Uniform/Normal, matching the reference's elementwise formulations).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply1
from paddle_tpu.tensor.random import default_generator

__all__ = ["Distribution", "Uniform", "Normal", "Categorical",
           "kl_divergence"]


def _as_tensor(v, dtype=jnp.float32):
    if isinstance(v, Tensor):
        return v
    return Tensor(jnp.asarray(v, dtype), stop_gradient=True)


class Distribution:
    """Abstract base (distribution.py:41)."""

    def __init__(self, name=None):
        self.name = name or type(self).__name__.lower()

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        """exp(log_prob) — the reference's direct-probability surface."""
        return apply1(jnp.exp, self.log_prob(value), name="probs")

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (distribution.py:168).  log_prob/probs follow the
    reference's clipped convention: values outside the support get
    probability 0."""

    def __init__(self, low, high, name=None):
        super().__init__(name)
        self.low = _as_tensor(low)
        self.high = _as_tensor(high)

    def sample(self, shape=(), seed=0):
        key = (jax.random.PRNGKey(seed) if seed else
               default_generator.split())
        shape = tuple(int(s) for s in np.atleast_1d(shape))

        def _s(lo, hi):
            out_shape = shape + jnp.broadcast_shapes(lo.shape, hi.shape)
            u = jax.random.uniform(key, out_shape, jnp.float32)
            return lo + (hi - lo) * u         # reparameterised
        return apply1(_s, self.low, self.high, name="uniform_sample")

    def log_prob(self, value):
        def _lp(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            lp = -jnp.log(hi - lo)
            return jnp.where(inside, lp, -jnp.inf)
        return apply1(_lp, _as_tensor(value), self.low, self.high,
                      name="uniform_log_prob")

    def entropy(self):
        return apply1(lambda lo, hi: jnp.log(hi - lo), self.low, self.high,
                      name="uniform_entropy")


class Normal(Distribution):
    """N(loc, scale) (distribution.py:390)."""

    def __init__(self, loc, scale, name=None):
        super().__init__(name)
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)

    def sample(self, shape=(), seed=0):
        key = (jax.random.PRNGKey(seed) if seed else
               default_generator.split())
        shape = tuple(int(s) for s in np.atleast_1d(shape))

        def _s(mu, sigma):
            out_shape = shape + jnp.broadcast_shapes(mu.shape, sigma.shape)
            eps = jax.random.normal(key, out_shape, jnp.float32)
            return mu + sigma * eps           # reparameterised
        return apply1(_s, self.loc, self.scale, name="normal_sample")

    def log_prob(self, value):
        def _lp(v, mu, sigma):
            var = sigma * sigma
            return (-((v - mu) ** 2) / (2 * var)
                    - jnp.log(sigma) - 0.5 * math.log(2 * math.pi))
        return apply1(_lp, _as_tensor(value), self.loc, self.scale,
                      name="normal_log_prob")

    def entropy(self):
        # 0.5 + 0.5 log(2π) + log σ (distribution.py:530)
        return apply1(
            lambda sigma: 0.5 + 0.5 * math.log(2 * math.pi) +
            jnp.log(sigma) + jnp.zeros_like(sigma),
            self.scale, name="normal_entropy")

    def kl_divergence(self, other: "Normal"):
        """KL(self || other) (distribution.py:595)."""
        if not isinstance(other, Normal):
            raise TypeError("kl_divergence expects another Normal")

        def _kl(mu0, s0, mu1, s1):
            var_ratio = (s0 / s1) ** 2
            t1 = ((mu0 - mu1) / s1) ** 2
            return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))
        return apply1(_kl, self.loc, self.scale, other.loc, other.scale,
                      name="normal_kl")


class Categorical(Distribution):
    """Categorical over unnormalised logits (distribution.py:640 — the
    reference's ``logits`` are *relative weights*, normalised by their
    sum; we accept either raw weights >=0 or real-valued logits via
    ``logits_are_log``)."""

    def __init__(self, logits, name=None, logits_are_log=False):
        super().__init__(name)
        self.logits = _as_tensor(logits)
        self._log_form = logits_are_log

    def _log_pmf(self):
        def _n(l):
            if self._log_form:
                return jax.nn.log_softmax(l, axis=-1)
            return jnp.log(l / jnp.sum(l, axis=-1, keepdims=True))
        return apply1(_n, self.logits, name="categorical_norm")

    def sample(self, shape=()):
        shape = tuple(int(s) for s in np.atleast_1d(shape))
        key = default_generator.split()
        lp = self._log_pmf()

        def _s(logp):
            batch = logp.shape[:-1]
            return jax.random.categorical(
                key, logp, axis=-1, shape=shape + batch)
        out = apply1(_s, lp, nondiff=(0,), name="categorical_sample")
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        lp = self._log_pmf()

        def _lp(logp, v):
            v = v.astype(jnp.int32)
            if logp.ndim == 1:
                # unbatched pmf scores every value against the same dist
                logp = jnp.broadcast_to(logp, v.shape + logp.shape)
            return jnp.take_along_axis(logp, v[..., None], axis=-1)[..., 0]
        return apply1(_lp, lp, _as_tensor(value, jnp.int32),
                      nondiff=(1,), name="categorical_log_prob")

    def probs(self, value):
        return apply1(jnp.exp, self.log_prob(value), name="probs")

    def entropy(self):
        lp = self._log_pmf()
        return apply1(lambda l: -jnp.sum(jnp.exp(l) * l, axis=-1), lp,
                      name="categorical_entropy")

    def kl_divergence(self, other: "Categorical"):
        if not isinstance(other, Categorical):
            raise TypeError("kl_divergence expects another Categorical")
        lp, lq = self._log_pmf(), other._log_pmf()
        return apply1(
            lambda a, b: jnp.sum(jnp.exp(a) * (a - b), axis=-1), lp, lq,
            name="categorical_kl")


def kl_divergence(p: Distribution, q: Distribution):
    """Functional form: paddle.distribution.kl_divergence."""
    return p.kl_divergence(q)
